"""Streaming scenario sweeps (mfm_tpu/scenario/sweep.py).

The subsystem's contracts:

- **Streaming == materializing.** A sweep keeps only a fixed-size carry
  (top-k worst per book, histogram sketch, counters) yet its answer is
  BITWISE the materializing engine's: for the same sampler and chunk,
  the streamed top-k (vol, scenario-index) table equals the reference
  built from ``ScenarioEngine.run``'s (S, K, K) covariances through the
  identical ``book_vols`` math — certified lanes and offender
  (exact-path) lanes alike.
- **Rejected lanes contaminate nothing.** A poisoned lane (NaN theta,
  corr_beta at the -1 pole) is counted rejected and excluded from the
  top-k, the histogram and n_ok; healthy batchmates' bytes don't move.
- **Steady state.** After one warm chunk per rung, further chunks
  compile NOTHING (the serving discipline: <= 1 compile per bucket).
- **The manifest is atomic and audited.** Round trip, torn-file
  detection, and ``audit_sweep_manifest`` rejecting hash drift.
- **Samplers are seeded generators.** Byte-deterministic per (seed, n,
  chunk); the replay library sweeps identity lanes over resolved
  windows.
- **Serving.** ``sweep`` is a guarded request kind with its own reason
  bit, and sweep lines are cache-exempt by contract.
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from mfm_tpu.grad.engine import ShockBall
from mfm_tpu.scenario import (
    ScenarioEngine,
    ScenarioSpec,
    GridSampler,
    ReplaySampler,
    SobolSampler,
    SweepEngine,
    SweepManifestError,
    UniformSampler,
    audit_sweep_manifest,
    build_sweep_manifest,
    monthly_replay_windows,
    read_sweep_manifest,
    sweep_manifest_path_for,
    theta_to_spec,
    write_sweep_manifest,
)
from mfm_tpu.scenario.kernel import book_vols
from mfm_tpu.utils.contracts import assert_max_compiles

K = 10


def _base_cov(seed=0, k=K, dtype=np.float32):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=(k, k))
    return ((a @ a.T + 1e-2 * np.eye(k)) * 1e-4).astype(dtype)


def _names(k=K):
    return [f"f{i}" for i in range(k)]


def _books(n=2, seed=5, k=K):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, k)) / np.sqrt(k)).astype(np.float32)


def _ball():
    # deliberately spicy: corr_beta up to 0.9 pushes some lanes past the
    # certificate so the offender exact path is exercised, not idle
    return ShockBall(shift_max=5e-3, scale_range=0.4, vol_mult_lo=1.0,
                     vol_mult_hi=3.0, corr_beta_lo=0.0, corr_beta_hi=0.9)


def _reference_table(engine, sampler, chunk, xs, top_k):
    """The materializing reference: every theta through
    ``ScenarioEngine.run`` (the exact forward path, PSD gate included),
    vols via the IDENTICAL ``book_vols`` math, top-k by descending vol
    with the stream's merge tie-break (earlier scenario index wins)."""
    import jax

    ths = np.concatenate([th for th, _, _ in sampler.blocks(chunk)])
    specs = [theta_to_spec(t, engine.factor_names, f"sweep-{i}")
             for i, t in enumerate(ths)]
    results = engine._scen.run(specs)
    ok = [i for i, r in enumerate(results) if r.ok]
    covs = np.stack([results[i].cov for i in ok])
    vols = np.asarray(jax.jit(book_vols)(jnp.asarray(covs),
                                         jnp.asarray(xs)))
    tables = []
    for b in range(xs.shape[0]):
        order = sorted(range(len(ok)), key=lambda j: (-vols[b, j], ok[j]))
        tables.append([(float(vols[b, j]), int(ok[j]))
                       for j in order[:top_k]])
    n_proj = sum(results[i].psd_projected for i in ok)
    return tables, vols, ok, n_proj


@pytest.fixture(scope="module")
def engine():
    return SweepEngine(_base_cov(), factor_names=_names())


# -- streaming == materializing parity ---------------------------------------

def test_streaming_top_k_bitwise_matches_materializing(engine):
    xs = _books()
    sampler = UniformSampler(_ball(), K, 600, seed=3)
    res = engine.sweep(xs, sampler, chunk=128, top_k=12, bins=64,
                      refine=None)
    assert res.counts["n_ok"] == 600 and res.counts["n_rejected"] == 0
    # the spicy ball must actually exercise the offender exact path
    assert res.counts["n_offenders"] > 0

    ref_sampler = UniformSampler(_ball(), K, 600, seed=3)
    ref_tables, vols, ok, n_proj = _reference_table(
        engine, ref_sampler, 128, xs, 12)
    for b, book in enumerate(res.books):
        got = [(e["vol"], e["src"]) for e in book["top"]]
        assert got == ref_tables[b], f"book {b} top-k diverged"
    assert res.counts["n_psd_projected"] == n_proj


def test_streaming_histogram_matches_materializing(engine):
    xs = _books()
    sampler = UniformSampler(_ball(), K, 384, seed=9)
    res = engine.sweep(xs, sampler, chunk=128, top_k=4, bins=32,
                      hist_span=8.0, refine=None)
    ref_sampler = UniformSampler(_ball(), K, 384, seed=9)
    _, vols, ok, _ = _reference_table(engine, ref_sampler, 128, xs, 4)
    for b, book in enumerate(res.books):
        lo, w = book["hist"]["lo"], book["hist"]["bin_width"]
        bins = len(book["hist"]["counts"])
        # the kernel's exact binning: clip into [0, bins-1]
        bi = np.clip(((vols[b] - np.float32(lo)) / np.float32(w))
                     .astype(np.int32), 0, bins - 1)
        want = np.bincount(bi, minlength=bins)
        np.testing.assert_array_equal(book["hist"]["counts"], want)
        assert sum(book["hist"]["counts"]) == len(ok)


def test_top1_spec_round_trips_through_materializing_engine(engine):
    """The worst case is REPLAYABLE: its embedded spec re-runs through
    the ordinary forward engine and lands on the identical vol."""
    import jax

    xs = _books()
    res = engine.sweep(xs, UniformSampler(_ball(), K, 256, seed=1),
                      chunk=128, top_k=4, refine=None)
    for b, book in enumerate(res.books):
        top = book["top"][0]
        spec = ScenarioSpec.from_dict(top["spec"])
        [r] = engine._scen.run([spec])
        assert r.ok, r.problems
        v = np.asarray(jax.jit(book_vols)(
            jnp.asarray(r.cov[None]), jnp.asarray(xs[b:b + 1])))[0, 0]
        assert float(v) == top["vol"]


# -- rejected-lane exclusion ---------------------------------------------------

class _PoisonSampler:
    """Wraps a sampler, overwriting chosen lanes with inadmissible
    thetas (NaN shift / corr_beta past the -1 pole)."""

    kind = "poison"

    def __init__(self, inner, poison_every=7):
        self.inner = inner
        self.cb_values = inner.cb_values
        self.windows = inner.windows
        self.n = inner.n
        self.every = poison_every

    def blocks(self, chunk):
        i = 0
        for th, bidx, lv in self.inner.blocks(chunk):
            th = th.copy()
            for j in range(len(th)):
                if (i + j) % self.every == 0:
                    if (i + j) % (2 * self.every) == 0:
                        th[j, 0] = np.nan
                    else:
                        th[j, -1] = -1.5
            i += len(th)
            yield th, bidx, lv

    def describe(self):
        return {"kind": self.kind, "n": self.n}


def test_rejected_lanes_excluded_and_counted(engine):
    xs = _books()
    inner = UniformSampler(_ball(), K, 256, seed=4)
    poisoned = _PoisonSampler(inner, poison_every=7)
    n_poison = len([i for i in range(256) if i % 7 == 0])
    res = engine.sweep(xs, poisoned, chunk=64, top_k=8, refine=None)
    assert res.counts["n_rejected"] == n_poison
    assert res.counts["n_ok"] == 256 - n_poison
    assert res.counts["n_scenarios"] == 256
    poisoned_src = {i for i in range(256) if i % 7 == 0}
    for book in res.books:
        assert not ({e["src"] for e in book["top"]} & poisoned_src)
        assert sum(book["hist"]["counts"]) == 256 - n_poison


def test_healthy_lanes_unmoved_by_poisoned_batchmates(engine):
    """The poisoned run's surviving top-k equals a clean run of ONLY the
    healthy lanes — per-lane isolation, streamed."""
    xs = _books()
    res_p = engine.sweep(xs, _PoisonSampler(
        UniformSampler(_ball(), K, 256, seed=4), 7), chunk=64, top_k=8,
        refine=None)
    ths = np.concatenate([
        th for th, _, _ in UniformSampler(_ball(), K, 256,
                                          seed=4).blocks(64)])
    healthy = [i for i in range(256) if i % 7 != 0]
    import jax
    specs = [theta_to_spec(ths[i], engine.factor_names, f"sweep-{i}")
             for i in healthy]
    results = engine._scen.run(specs)
    covs = np.stack([r.cov for r in results])
    vols = np.asarray(jax.jit(book_vols)(jnp.asarray(covs),
                                         jnp.asarray(xs)))
    for b, book in enumerate(res_p.books):
        order = sorted(range(len(healthy)),
                       key=lambda j: (-vols[b, j], healthy[j]))
        want = [(float(vols[b, j]), healthy[j]) for j in order[:8]]
        assert [(e["vol"], e["src"]) for e in book["top"]] == want


# -- steady-state compile discipline ------------------------------------------

def test_steady_state_zero_compiles_across_two_rungs(engine):
    xs = _books()
    ball = ShockBall(shift_max=1e-3, scale_range=0.2, vol_mult_hi=2.0,
                     corr_beta_hi=0.2)
    # warm both chunk rungs (and the merge path) once
    engine.sweep(xs, UniformSampler(ball, K, 64, seed=0), chunk=32,
                 refine=None)
    engine.sweep(xs, UniformSampler(ball, K, 256, seed=0), chunk=128,
                 refine=None)
    with assert_max_compiles(0, "sweep steady state, two chunk rungs"):
        r1 = engine.sweep(xs, UniformSampler(ball, K, 64, seed=8),
                          chunk=32, refine=None)
        r2 = engine.sweep(xs, UniformSampler(ball, K, 256, seed=8),
                          chunk=128, refine=None)
    assert r1.counts["n_ok"] == 64 and r2.counts["n_ok"] == 256


# -- samplers ------------------------------------------------------------------

def test_uniform_sampler_byte_deterministic():
    a = UniformSampler(_ball(), K, 300, seed=12)
    b = UniformSampler(_ball(), K, 300, seed=12)
    for (ta, ia, la), (tb, ib, lb) in zip(a.blocks(64), b.blocks(64)):
        assert ta.tobytes() == tb.tobytes()
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ia, ib)
    # a different seed moves the draws
    c = UniformSampler(_ball(), K, 300, seed=13)
    assert next(iter(c.blocks(64)))[0].tobytes() != \
        next(iter(a.blocks(64)))[0].tobytes()


def test_grid_sampler_covers_the_plane():
    g = GridSampler(_ball(), K, n_vol=5, n_corr=7)
    ths = np.concatenate([th for th, _, _ in g.blocks(8)])
    assert len(ths) == 35
    assert len(np.unique(ths[:, 2 * K])) == 5
    assert len(np.unique(ths[:, 2 * K + 1])) == 7
    # vol shifts/scales stay neutral on the grid slice
    assert (ths[:, :K] == 0).all() and (ths[:, K:2 * K] == 1).all()


def test_sobol_sampler_records_its_engine():
    s = SobolSampler(_ball(), K, 64, seed=2)
    d = s.describe()
    assert d["kind"] == "sobol"
    assert d["qmc"] in ("sobol", "uniform-fallback")
    ths = np.concatenate([th for th, _, _ in s.blocks(32)])
    assert ths.shape == (64, 2 * K + 2)
    lo, hi = _ball().bounds(K)
    assert (ths >= np.asarray(lo) - 1e-12).all()
    assert (ths <= np.asarray(hi) + 1e-12).all()


def test_monthly_replay_windows_and_sampler():
    dates = (list(np.arange("2024-01-03", "2024-01-20",
                            dtype="datetime64[D]"))
             + list(np.arange("2024-02-01", "2024-02-15",
                              dtype="datetime64[D]")))
    wins = monthly_replay_windows(dates)
    assert wins == [("2024-01-03", "2024-01-19"),
                    ("2024-02-01", "2024-02-14")]
    rs = ReplaySampler(wins, K)
    blocks = list(rs.blocks(8))
    th, bidx, lv = blocks[0]
    assert len(th) == 2
    np.testing.assert_array_equal(bidx, [1, 2])     # rows into the library
    assert (th[:, :K] == 0).all() and (th[:, 2 * K] == 1).all()


def test_replay_sweep_serves_windows_identity(engine):
    """A replay sweep resolves windows through replay_lookup and serves
    each window's covariance back through the identity transform."""
    import jax

    win_cov = _base_cov(seed=7)

    def lookup(start, end):
        assert (start, end) == ("2024-01-02", "2024-01-31")
        return win_cov

    eng = SweepEngine(_base_cov(), factor_names=_names(),
                      replay_lookup=lookup)
    xs = _books(1)
    res = eng.sweep(xs, ReplaySampler([("2024-01-02", "2024-01-31")], K),
                    chunk=8, top_k=2, refine=None)
    assert res.counts["n_ok"] == 1
    top = res.books[0]["top"][0]
    assert top["base_window"] == ["2024-01-02", "2024-01-31"]
    v = np.asarray(jax.jit(book_vols)(
        jnp.asarray(win_cov.astype(np.float32)[None]),
        jnp.asarray(xs)))[0, 0]
    assert top["vol"] == float(v)
    spec = ScenarioSpec.from_dict(top["spec"])
    assert spec.replay == ("2024-01-02", "2024-01-31")


def test_unresolvable_window_rejects_its_lanes(engine):
    eng = SweepEngine(_base_cov(), factor_names=_names(),
                      replay_lookup=lambda s, e: None)
    xs = _books(1)
    res = eng.sweep(xs, ReplaySampler([("1999-01-01", "1999-01-31")], K),
                    chunk=8, top_k=2, refine=None)
    assert res.counts["n_ok"] == 0 and res.counts["n_rejected"] == 1
    assert res.sampler.get("window_problems")


# -- manifest ------------------------------------------------------------------

def _small_result(engine):
    return engine.sweep(_books(), UniformSampler(_ball(), K, 64, seed=6),
                        chunk=32, top_k=4, refine=None)


def test_manifest_round_trip_and_audit(engine, tmp_path):
    res = _small_result(engine)
    man = build_sweep_manifest(res, backend="cpu", staleness=0,
                               summary={"trace_id": "t" * 32})
    path = write_sweep_manifest(str(tmp_path), man)
    assert path == sweep_manifest_path_for(str(tmp_path))
    back = read_sweep_manifest(path)
    assert back["sweep"]["counts"] == res.counts
    problems, warnings = audit_sweep_manifest(path)
    assert problems == []


def test_manifest_torn_write_detected(tmp_path):
    path = str(tmp_path / "sweep_manifest.json")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"schema_version": 1, "kind": "sweep_man')
    with pytest.raises(SweepManifestError):
        read_sweep_manifest(path)


def test_manifest_audit_catches_spec_hash_drift(engine, tmp_path):
    res = _small_result(engine)
    man = build_sweep_manifest(res)
    # corrupt one embedded top entry's recorded hash
    man["sweep"]["books"][0]["top"][0]["spec_hash"] = "0" * 64
    path = write_sweep_manifest(str(tmp_path), man)
    problems, _ = audit_sweep_manifest(path)
    assert problems, "hash drift must be a problem"


# -- serving -------------------------------------------------------------------

def _qengine():
    from mfm_tpu.serve import QueryEngine
    return QueryEngine(_base_cov(), factor_names=_names())


def _sweep_line(rid="s0", **sweep):
    return json.dumps({"id": rid, "weights": [1.0 / K] * K,
                       "sweep": sweep or True})


def test_parse_request_sweep_bits():
    from mfm_tpu.serve import ServePolicy, parse_request
    from mfm_tpu.serve.server import REQ_REASON_BAD_SWEEP

    eng = _qengine()
    fields, mask, _ = parse_request(
        _sweep_line(n=128, chunk=64, top_k=4), eng, ServePolicy())
    assert mask == 0
    assert fields[-1] == {"sampler": "uniform", "n": 128, "chunk": 64,
                          "top_k": 4, "bins": 64, "seed": 0}
    for bad in ({"sampler": "bogus"}, {"n": 10 ** 9}, {"n": 0},
                {"chunk": -1}, {"top_k": 1.5}, "not-a-spec"):
        line = json.dumps({"id": "x", "weights": [0.1] * K, "sweep": bad})
        _, mask, detail = parse_request(line, eng, ServePolicy())
        assert mask & REQ_REASON_BAD_SWEEP, (bad, detail)
    both = json.dumps({"id": "x", "weights": [0.1] * K, "sweep": True,
                       "construct": "min_vol"})
    _, mask, _ = parse_request(both, eng, ServePolicy())
    assert mask & REQ_REASON_BAD_SWEEP


def test_server_answers_sweep_requests():
    import io
    from mfm_tpu.serve import QueryServer, ServePolicy

    srv = QueryServer(_qengine(), ServePolicy())
    out = io.StringIO()
    lines = [_sweep_line("s0", n=64, chunk=32, top_k=4, seed=3),
             json.dumps({"id": "q0", "weights": [1.0 / K] * K})]
    srv.run(iter(lines), out)
    got = {json.loads(ln)["id"]: json.loads(ln)
           for ln in out.getvalue().strip().splitlines()}
    assert got["q0"]["outcome"] == "ok" and "book" not in got["q0"]
    sw = got["s0"]
    assert sw["outcome"] == "ok" and sw["kind"] == "sweep"
    assert sw["counts"]["n_ok"] == 64
    assert len(sw["book"]["top"]) == 4
    assert sw["book"]["vol_base"] > 0


def test_sweep_requests_are_cache_exempt():
    from mfm_tpu.serve.cache import ResponseCache

    cache = ResponseCache()
    assert cache.key_for(_sweep_line(n=64)) is None
    assert cache.lookup(_sweep_line(n=64)) == (None, None)
    plain = json.dumps({"id": "q", "weights": [0.1] * K})
    assert cache.key_for(plain) is not None
