"""Parity: rolling-window kernels vs the reference's pandas/WLS recipes.

Uses small windows (so tests are fast) — the kernels take window/half-life/
min_periods as parameters, and the goldens use the identical parameters, so
small-window agreement implies the full-size contracts.
"""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from mfm_tpu.ops.rolling import (
    rolling_beta_hsigma,
    rolling_cmra,
    rolling_decay_weighted_mean,
    rolling_sum,
    rolling_weighted_std,
)

import golden


@pytest.fixture(scope="module")
def series():
    rng = np.random.default_rng(42)
    T, N = 160, 7
    mkt = 0.01 * rng.standard_normal(T)
    ret = 0.8 * mkt[:, None] + 0.015 * rng.standard_normal((T, N))
    # missing patterns: leading NaNs (late listing), interior holes (suspension)
    ret[:30, 1] = np.nan
    ret[50:70, 2] = np.nan
    ret[rng.random((T, N)) < 0.05] = np.nan
    ret[:, 3] = np.nan  # never enough data
    return ret, mkt


def test_beta_hsigma_matches_statsmodels_recipe(series):
    ret, mkt = series
    T, HL, MINP = 60, 15, 12
    beta, hsigma = rolling_beta_hsigma(
        jnp.asarray(ret), jnp.asarray(mkt),
        window=T, half_life=HL, min_periods=MINP, block=32,
    )
    beta, hsigma = np.asarray(beta), np.asarray(hsigma)
    for n in range(ret.shape[1]):
        gb, gh = golden.golden_beta_hsigma(
            pd.Series(ret[:, n]), pd.Series(mkt), T=T, hl=HL, minp=MINP
        )
        np.testing.assert_allclose(beta[:, n], gb, rtol=1e-7, atol=1e-10, equal_nan=True)
        np.testing.assert_allclose(hsigma[:, n], gh, rtol=1e-7, atol=1e-10, equal_nan=True)


def test_rstr_matches_pandas_recipe(series):
    ret, _ = series
    logret = np.log1p(ret)
    T, L, HL, MINP = 80, 5, 20, 10
    W = T - L
    shifted = np.full_like(logret, np.nan)
    shifted[L:] = logret[:-L]
    got = np.asarray(
        rolling_decay_weighted_mean(
            jnp.asarray(shifted), window=W, half_life=HL, min_periods=MINP, block=32
        )
    )
    for n in range(ret.shape[1]):
        g = golden.golden_rstr(pd.Series(logret[:, n]), T=T, L=L, hl=HL, minp=MINP)
        np.testing.assert_allclose(got[:, n], g, rtol=1e-8, atol=1e-12, equal_nan=True)


def test_dastd_matches_pandas_recipe(series):
    ret, mkt = series
    excess = ret - mkt[:, None]
    T, HL, MINP = 60, 12, 12
    got = np.asarray(
        rolling_weighted_std(
            jnp.asarray(excess), window=T, half_life=HL, min_periods=MINP, block=32
        )
    )
    for n in range(ret.shape[1]):
        g = golden.golden_dastd(pd.Series(excess[:, n]), T=T, hl=HL, minp=MINP)
        np.testing.assert_allclose(got[:, n], g, rtol=1e-8, atol=1e-12, equal_nan=True)


def test_cmra_matches_pandas_recipe(series):
    ret, _ = series
    logret = np.log1p(ret)
    T = 40
    got = np.asarray(rolling_cmra(jnp.asarray(logret), window=T, block=32))
    for n in range(ret.shape[1]):
        g = golden.golden_cmra(pd.Series(logret[:, n]), T=T)
        np.testing.assert_allclose(got[:, n], g, rtol=1e-8, atol=1e-12, equal_nan=True)


def test_rolling_sum_matches_pandas(series):
    ret, _ = series
    x = np.abs(ret)
    got = np.asarray(rolling_sum(jnp.asarray(x), window=21, min_periods=15, block=32))
    for n in range(x.shape[1]):
        g = pd.Series(x[:, n]).rolling(21, min_periods=15).sum().to_numpy()
        np.testing.assert_allclose(got[:, n], g, rtol=1e-10, atol=1e-14, equal_nan=True)


def test_auto_block_matches_measured_sweep():
    from mfm_tpu.ops.rolling import auto_block

    assert auto_block(300) == 64     # CSI300: largest block wins
    assert auto_block(5000) == 16    # all-A: the measured optimum
    assert auto_block(100_000) == 8  # floor: never below lo
    assert auto_block(1) == 64       # cap: never above hi
    # the budget is element-size aware: f64 halves the fitting block
    assert auto_block(5000, itemsize=8) == 8


def test_factor_engine_resolves_auto_block():
    import jax.numpy as jnp
    import pytest

    from mfm_tpu.config import PipelineConfig
    from mfm_tpu.factors.engine import FactorEngine

    f32 = jnp.float32
    eng = FactorEngine({"close": jnp.zeros((4, 300), f32)}, jnp.zeros(4, f32))
    assert eng.block == 64
    eng = FactorEngine({"close": jnp.zeros((4, 5000), f32)}, jnp.zeros(4, f32))
    assert eng.block == 16
    # the resolution is dtype-aware (f64 doubles the per-element cost)...
    eng = FactorEngine({"close": jnp.zeros((4, 5000), jnp.float64)},
                       jnp.zeros(4))
    assert eng.block == 8
    # ...and an explicit block always wins
    eng = FactorEngine({"close": jnp.zeros((4, 5000), f32)}, jnp.zeros(4, f32),
                       block=32)
    assert eng.block == 32

    assert PipelineConfig(block=None).block is None
    with pytest.raises(ValueError):
        PipelineConfig(block=0)


def test_factor_engine_auto_block_respects_config_windows():
    import jax.numpy as jnp

    from mfm_tpu.config import FactorConfig, RollingSpec
    from mfm_tpu.factors.engine import FactorEngine

    wide = FactorConfig(beta=RollingSpec(window=1008, half_life=63,
                                         min_periods=42))
    eng = FactorEngine({"close": jnp.zeros((4, 5000), jnp.float32)},
                       jnp.zeros(4, jnp.float32), config=wide)
    assert eng.block == 8  # 2x window halves the fitting block (was 16)


class TestScanVsBlock:
    """The O(T*N) two-level scan path must agree with the windowed-gather
    block path (the reference formulation) on every kernel, under ragged
    NaN patterns, short heads, and T not a multiple of the window."""

    def _panel(self, T=137, N=7, seed=3, nan_frac=0.25):
        rng = np.random.default_rng(seed)
        x = rng.normal(0.001, 0.02, (T, N))
        mask = rng.random((T, N)) < nan_frac
        x[mask] = np.nan
        x[:11, 0] = np.nan          # late listing
        x[:, 1] = np.nan            # never valid
        x[60:90, 2] = np.nan        # suspension
        return jnp.asarray(x)

    def test_rolling_sum(self):
        x = self._panel()
        for window, mp in ((21, 15), (63, 42), (130, 90)):
            a = rolling_sum(x, window=window, min_periods=mp, impl="scan")
            b = rolling_sum(x, window=window, min_periods=mp, impl="block")
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-12, atol=1e-14)

    def test_beta_hsigma(self):
        y = self._panel(seed=4)
        mkt = jnp.asarray(np.random.default_rng(5).normal(0.0005, 0.01, 137))
        ba, ha = rolling_beta_hsigma(y, mkt, window=60, half_life=15,
                                     min_periods=10, impl="scan")
        bb, hb = rolling_beta_hsigma(y, mkt, window=60, half_life=15,
                                     min_periods=10, impl="block")
        np.testing.assert_allclose(np.asarray(ba), np.asarray(bb),
                                   rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(np.asarray(ha), np.asarray(hb),
                                   rtol=1e-9, atol=1e-12)

    def test_weighted_std(self):
        x = self._panel(seed=6)
        a = rolling_weighted_std(x, window=60, half_life=12, min_periods=10,
                                 impl="scan")
        b = rolling_weighted_std(x, window=60, half_life=12, min_periods=10,
                                 impl="block")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-13)

    def test_decay_weighted_mean(self):
        x = self._panel(seed=7)
        a = rolling_decay_weighted_mean(x, window=50, half_life=13,
                                        min_periods=8, impl="scan")
        b = rolling_decay_weighted_mean(x, window=50, half_life=13,
                                        min_periods=8, impl="block")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-13)

    def test_cmra(self):
        x = self._panel(seed=8, nan_frac=0.02)
        a = rolling_cmra(x, window=40, impl="scan")
        b = rolling_cmra(x, window=40, impl="block")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-12)

    def test_window_equals_T_and_window_exceeds_T(self):
        x = self._panel(T=50, N=4, seed=9)
        for window in (50, 64):
            a = rolling_sum(x, window=window, min_periods=5, impl="scan")
            b = rolling_sum(x, window=window, min_periods=5, impl="block")
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-12, atol=1e-14)


def test_scan_float32_drift():
    """Pin the measured float32 drift of the scan path's moment-form
    identities vs the float64 reference (docstring of rolling_beta_hsigma):
    the normal-equation ssr cancels as R^2 -> 1, so the bound is driven by
    an index-tracker-like column; typical columns sit at ~1e-7 medians."""
    rng = np.random.default_rng(0)
    T, N = 800, 6
    mkt = rng.normal(0.0005, 0.012, T)
    y = np.empty((T, N))
    for i in range(4):
        y[:, i] = 0.8 * mkt + rng.normal(0, 0.015, T)
    y[:, 4] = 1.0 * mkt + rng.normal(0, 0.0004, T)   # tracker, R^2 ~ 0.999
    y[:, 5] = rng.normal(0, 0.02, T)
    y[rng.random((T, N)) < 0.1] = np.nan

    y32 = jnp.asarray(y.astype(np.float32))
    m32 = jnp.asarray(mkt.astype(np.float32))
    bs, hs = rolling_beta_hsigma(y32, m32, impl="scan")
    bt, ht = rolling_beta_hsigma(jnp.asarray(y), jnp.asarray(mkt),
                                 impl="block")

    def rel(a, ref):
        a = np.asarray(a, np.float64)
        ref = np.asarray(ref, np.float64)
        ok = np.isfinite(ref) & np.isfinite(a)
        assert (np.isfinite(ref) == np.isfinite(a)).all()
        return np.abs(a - ref)[ok] / np.maximum(np.abs(ref[ok]), 1e-12)

    for arr, truth in ((bs, bt), (hs, ht)):
        d = rel(arr, truth)
        assert np.max(d) < 5e-4, np.max(d)
        assert np.median(d) < 2e-6, np.median(d)


def test_decay_windowed_sums_scan_brute_force():
    """Unit-level pin of the two-level machinery itself: random masked terms
    and a random nondecreasing expo (event-time-like, including flat runs and
    jumps), checked against a brute-force O(T*W) loop at chunk boundaries,
    window == T, and T % window != 0."""
    from mfm_tpu.ops.rolling import decay_windowed_sums_scan

    rng = np.random.default_rng(13)
    T, N = 97, 3
    term = rng.normal(size=(T, N))
    term[rng.random((T, N)) < 0.3] = 0.0  # pre-zeroed invalids
    expo = np.cumsum(rng.integers(0, 3, (T, N)), axis=0).astype(float)
    for window, lam in ((1, 0.9), (2, 0.9), (13, 0.9), (40, 0.97),
                        (97, 0.95), (30, 1.0 / 0.9)):
        (got,) = decay_windowed_sums_scan(
            [jnp.asarray(term)], window, jnp.asarray(expo), lam)
        ref = np.zeros((T, N))
        for t in range(T):
            for j in range(max(0, t - window + 1), t + 1):
                ref[t] += lam ** (expo[t] - expo[j]) * term[j]
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-10,
                                   atol=1e-12)


def test_windowed_max_scan_brute_force():
    from mfm_tpu.ops.rolling import windowed_max_scan

    rng = np.random.default_rng(14)
    T, N = 101, 4
    x = rng.normal(size=(T, N))
    x[rng.random((T, N)) < 0.2] = -np.inf  # masked entries, as callers pass
    for window in (7, 25, 101, 120):
        got = np.asarray(windowed_max_scan(jnp.asarray(x), window))
        ref = np.stack([
            np.max(x[max(0, t - window + 1): t + 1], axis=0)
            for t in range(T)
        ])
        np.testing.assert_array_equal(got, ref)
