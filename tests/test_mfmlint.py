"""The doctrine linter, gated into tier-1.

Three layers:
 1. the real tree lints clean — zero violations over ``mfm_tpu bench.py
    tools`` with an EMPTY committed baseline (the grandfathered host-side
    planners were rewritten; nothing is suppressed anymore), which is
    what makes every rule here a regression gate;
 2. per-rule fixture snippets (positive + negative) pin each rule's
    semantics, including the conservative call graph (helpers reachable
    only from un-traced CLI paths are NOT flagged);
 3. injection drills on scratch copies of real modules: flipping a real
    s32 ``fori_loop`` bound back to a python int, or adding a
    post-donation use to ``risk_model.py``, must make the CLI exit
    non-zero — proof the gate would have caught the original incidents.

No jax import here: the linter is pure-AST and these tests stay cheap.
"""

import json
import shutil
import textwrap
from pathlib import Path

from mfm_tpu.lint import (
    DEFAULT_BASELINE,
    REPO_ROOT,
    load_baseline,
    main,
    run_lint,
)

REPO = Path(REPO_ROOT)


def _lint(tmp_path, files, baseline=None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint([str(tmp_path)], baseline=baseline, root=str(tmp_path))


def _rules(res):
    return sorted({v.rule for v in res.new})


# -- layer 1: the real tree ---------------------------------------------------

def test_repo_lints_clean_with_committed_baseline():
    baseline = load_baseline(str(REPO / DEFAULT_BASELINE))
    # the baseline burned down to zero (the host-side Brent-Luk planners
    # went pure-python, the tool timing spans force explicitly) — it must
    # never grow back without a fight
    assert baseline == [], "baseline creep: fix the violation instead"
    res = run_lint(["mfm_tpu", "bench.py", "tools"], baseline=baseline)
    assert not res.new, "\n".join(v.render() for v in res.new)
    assert not res.stale, f"stale baseline entries: {res.stale}"
    assert not res.baselined


# -- layer 2: per-rule fixtures ----------------------------------------------

def test_r1_np_in_traced_flagged_and_callgraph_spares_cli(tmp_path):
    res = _lint(tmp_path, {"mod.py": """
        import numpy as np
        import jax
        import jax.numpy as jnp

        def helper(x):
            return np.mean(x)          # reachable from the jit below: R1

        def cli_helper(x):
            return np.median(x)        # only called from main(): clean

        @jax.jit
        def traced(x):
            return helper(x) + jnp.sum(x)

        def main(x):
            return cli_helper(x)
    """})
    assert [v.rule for v in res.new] == ["R1"]
    assert res.new[0].qualname == "helper"
    assert "np.mean" in res.new[0].message


def test_r1_dtype_plumbing_allowed_in_traced(tmp_path):
    res = _lint(tmp_path, {"mod.py": """
        import numpy as np
        import jax
        import jax.numpy as jnp

        @jax.jit
        def traced(x):
            eps = np.finfo(np.float32).eps
            return x.astype(np.float32) + eps
    """})
    assert not res.new


def test_r2_unpinned_arange_and_s64_astype(tmp_path):
    res = _lint(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            idx = jnp.arange(x.shape[0])          # R2: unpinned
            return x[idx].astype(int)             # R2: python int -> s64

        @jax.jit
        def good(x):
            idx = jnp.arange(x.shape[0], dtype=jnp.int32)
            f = jnp.arange(0.0, 1.0, 0.1)         # float arange: fine
            return x[idx].astype(jnp.int32) + f.sum()

        def host(n):
            return jnp.arange(n)                  # un-traced: not R2's scope
    """})
    assert [v.rule for v in res.new] == ["R2", "R2"]
    assert all(v.qualname == "bad" for v in res.new)


def test_r2_fori_loop_bounds(tmp_path):
    res = _lint(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            return jax.lax.fori_loop(0, 7 * 3, lambda i, c: c + i, x)

        @jax.jit
        def good(x, hi):
            return jax.lax.fori_loop(jnp.int32(0), hi.astype(jnp.int32),
                                     lambda i, c: c + i, x)
    """})
    assert [v.rule for v in res.new] == ["R2", "R2"]  # both bounds of `bad`
    assert all(v.qualname == "bad" for v in res.new)


def test_r2_eigen_carry_date_step_shape(tmp_path):
    """Fixture shaped like the incremental eigen date step
    (models/eigen.py::eigen_risk_adjust_incremental): a fori_loop that
    consumes one draw column per date from a carried (R, p, n) triple.
    The hazards R2 exists for — an s64 loop bound from bare Python ints
    and an unpinned arange over the chunk axis — must be flagged in the
    carry-step shape, while the production-shaped form stays clean."""
    res = _lint(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad_carry(covs, draws, R, p, n):
            def date_step(t, carry):
                R, p, n = carry
                x = jax.lax.dynamic_index_in_dim(draws, n, axis=-1,
                                                 keepdims=False)
                return R + x[..., None] * x[..., None, :], p + x, n + 1
            order = jnp.arange(covs.shape[0])      # R2: unpinned iota
            R, p, n = jax.lax.fori_loop(0, 8 * 4, date_step, (R, p, n))
            return R, p, n, order

        @jax.jit
        def good_carry(covs, draws, R, p, n):
            def date_step(t, carry):
                R, p, n = carry
                x = jax.lax.dynamic_index_in_dim(draws, n, axis=-1,
                                                 keepdims=False)
                return R + x[..., None] * x[..., None, :], p + x, n + 1
            order = jnp.arange(covs.shape[0], dtype=jnp.int32)
            hi = jnp.int32(covs.shape[0])
            R, p, n = jax.lax.fori_loop(jnp.int32(0), hi, date_step,
                                        (R, p, n))
            return R, p, n, order
    """})
    assert all(v.rule == "R2" for v in res.new)
    assert res.new, "R2 missed the s64 hazards in the carry-step shape"
    assert all(v.qualname.startswith("bad_carry") for v in res.new)


def test_r3_config_update_placement_and_duplicates(tmp_path):
    res = _lint(tmp_path, {
        "mfm_tpu/deep/worker.py": """
            import jax
            jax.config.update("jax_enable_x64", True)   # R3: not entrypoint
        """,
        "tools/capture.py": """
            import jax
            jax.config.update("jax_platforms", "cpu")    # entrypoint: fine
            jax.config.update("jax_enable_x64", True)    # distinct key: fine
            jax.config.update("jax_platforms", "tpu")    # R3: duplicate key
        """})
    got = {(v.file.replace("\\", "/"), v.rule) for v in res.new}
    assert got == {("mfm_tpu/deep/worker.py", "R3"),
                   ("tools/capture.py", "R3")}


def test_r4_use_after_donation(tmp_path):
    res = _lint(tmp_path, {"mod.py": """
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, donate_argnums=(0,))
        def step(x, y):
            return x + y

        def bad(a, b):
            out = step(a, b)
            return out + a            # R4: a was donated into step

        def good(a, b):
            a = step(a, b)            # rebound: the old buffer is gone
            return a + b

        def also_good(a, b):
            out = step(a, b)
            return out + b            # b was not donated
    """})
    assert [v.rule for v in res.new] == ["R4"]
    assert res.new[0].qualname == "bad"
    assert "'a'" in res.new[0].message


def test_r5_unforced_timing_span_in_tools(tmp_path):
    files = {
        "tools/bench_like.py": """
            import time
            import jax.numpy as jnp
            import numpy as np

            def unforced(x):
                t0 = time.perf_counter()
                y = jnp.sum(x)                    # R5: dispatch, not compute
                return time.perf_counter() - t0, y

            def forced(x):
                t0 = time.perf_counter()
                y = jnp.sum(x).block_until_ready()
                return time.perf_counter() - t0, y

            def host_golden(x):
                t0 = time.perf_counter()
                y = np.sum(x)                     # pure numpy: synchronous
                return time.perf_counter() - t0, y
        """,
        # same unforced span OUTSIDE bench/tools: not R5's scope
        "mfm_tpu/inner.py": """
            import time
            import jax.numpy as jnp

            def unforced(x):
                t0 = time.perf_counter()
                y = jnp.sum(x)
                return time.perf_counter() - t0, y
        """}
    res = _lint(tmp_path, files)
    assert [(v.rule, v.qualname) for v in res.new] == [("R5", "unforced")]
    assert "bench_like" in res.new[0].file


def test_r5_eigen_sweep_cell_timing(tmp_path):
    """Fixture shaped like a tools/profile_eigen.py sweep cell: a wall
    measured around a jitted eigen-stage call.  An unforced span (the jit
    call dispatches and returns before the work runs) must be flagged;
    the production shape — forcing through a host conversion before
    reading the clock — must stay clean."""
    res = _lint(tmp_path, {"tools/sweep_like.py": """
        import time
        import numpy as np
        import jax
        import jax.numpy as jnp

        def unforced_cell(stage, covs, valid, sim_covs):
            t0 = time.perf_counter()
            out = jax.jit(stage)(covs, valid, sim_covs)  # R5: dispatch only
            return time.perf_counter() - t0, out

        def forced_cell(stage, covs, valid, sim_covs):
            t0 = time.perf_counter()
            out = float(np.asarray(jnp.nansum(jax.jit(stage)(
                covs, valid, sim_covs))))
            return time.perf_counter() - t0, out
    """})
    assert [(v.rule, v.qualname) for v in res.new] == \
        [("R5", "unforced_cell")]


def test_r6_partition_spec_axes(tmp_path):
    res = _lint(tmp_path, {
        "parallel/mesh.py": """
            from jax.sharding import Mesh
            def make(devs):
                return Mesh(devs, ("row", "col"))
        """,
        "specs.py": """
            from jax.sharding import PartitionSpec as P
            GOOD = P("row", None)
            ALSO = P(("row", "col"))
            BAD = P("model")           # R6: not a doctrine axis
        """})
    assert [v.rule for v in res.new] == ["R6"]
    assert "'model'" in res.new[0].message
    assert "row" in res.new[0].message  # the legal axes are named


def test_r7_telemetry_in_traced_code(tmp_path):
    """Telemetry (mfm_tpu.obs / utils.obs) must stay host-side: direct or
    transitively-reachable calls from traced code are R7; the same calls on
    the host path around the jit boundary are clean."""
    res = _lint(tmp_path, {
        "mfm_tpu/utils/obs.py": """
            def log(level, event, **fields):
                pass
        """,
        "mfm_tpu/obs/instrument.py": """
            def record_update_latency(seconds):
                pass
        """,
        "mfm_tpu/model.py": """
            import jax
            import jax.numpy as jnp
            from mfm_tpu.obs import instrument
            from mfm_tpu.utils.obs import log

            def helper(x):
                log("info", "inner")                    # traced-reachable: R7
                return x * 2

            @jax.jit
            def bad(x):
                log("info", "step")                     # R7: utils.obs
                instrument.record_update_latency(0.1)   # R7: obs package
                return jnp.sum(helper(x))

            def host(x):
                y = bad(x)
                log("info", "done")                     # host side: fine
                instrument.record_update_latency(0.1)
                return y
        """})
    got = sorted((v.rule, v.qualname) for v in res.new)
    assert got == [("R7", "bad"), ("R7", "bad"), ("R7", "helper")]


def test_r7_trace_and_profile_in_traced_code(tmp_path):
    """The tracing/profiling additions (obs/trace.py, obs/profile.py) are
    telemetry like the rest of mfm_tpu.obs: a span opened or a profile
    pulled inside traced code is R7; the same calls bracketing the jit
    boundary from the host — and from the host-only serving loop — are
    clean."""
    res = _lint(tmp_path, {
        "mfm_tpu/obs/trace.py": """
            def start_span(name, **attrs):
                return object()

            def end_span(sp, **attrs):
                return sp
        """,
        "mfm_tpu/obs/profile.py": """
            def executable_profile(fn, *args):
                return {}
        """,
        "mfm_tpu/model.py": """
            import jax
            import jax.numpy as jnp
            from mfm_tpu.obs import profile
            from mfm_tpu.obs.trace import end_span, start_span

            def stepper(x):
                sp = start_span("inner")            # traced-reachable: R7
                y = x * 2
                end_span(sp)                        # traced-reachable: R7
                return y

            @jax.jit
            def bad(x):
                profile.executable_profile(None)    # R7: obs.profile
                return jnp.sum(stepper(x))

            def host(x):
                sp = start_span("update")           # host side: fine
                y = bad(x)
                end_span(sp)
                profile.executable_profile(bad, x)  # host side: fine
                return y
        """,
        "mfm_tpu/serve/server.py": """
            from mfm_tpu.obs.trace import end_span, start_span

            class QueryServer:
                def drain(self):
                    sp = start_span("serve.batch")  # host-only module: fine
                    return end_span(sp, outcome="ok")
        """})
    got = sorted((v.rule, v.qualname) for v in res.new)
    assert got == [("R7", "bad"), ("R7", "stepper"), ("R7", "stepper")]


def test_r7_scenario_host_only_barrier(tmp_path):
    """mfm_tpu.scenario.engine / .manifest are host-only: their obs calls
    and IO are never R7, and ``ScenarioEngine.run``'s bare-name collision
    with a traced ``run`` must not drag the host engine's telemetry into
    the traced set.  The device kernel (scenario/kernel.py) is NOT on the
    host-only list — a doctrine violation there still flags."""
    res = _lint(tmp_path, {
        "mfm_tpu/obs/instrument.py": """
            def record_scenario_batch(n, seconds):
                pass
        """,
        "mfm_tpu/scenario/engine.py": """
            from mfm_tpu.obs.instrument import record_scenario_batch

            class ScenarioEngine:
                def run(self, specs):   # collides with RiskModel.run by name
                    record_scenario_batch(len(specs), 0.1)
                    return specs
        """,
        "mfm_tpu/scenario/manifest.py": """
            import json
            import os
            from mfm_tpu.obs.instrument import record_scenario_batch

            def write_scenario_manifest(path, manifest):
                record_scenario_batch(1, 0.0)
                with open(path, "w") as fh:
                    json.dump(manifest, fh)
        """,
        "mfm_tpu/models/risk_model.py": """
            import jax
            import jax.numpy as jnp

            class RiskModel:
                def run(self, x):
                    return jnp.sum(x)

            @jax.jit
            def traced(model, x):
                return model.run(x)   # bare-name resolution: host-only
                                      # modules must not be candidates
        """,
        "mfm_tpu/scenario/kernel.py": """
            import numpy as np
            import jax
            import jax.numpy as jnp

            @jax.jit
            def scenario_batch(x):
                return jnp.asarray(np.mean(x))   # R1: np math in traced code
        """})
    assert [(v.rule, v.qualname) for v in res.new] == \
        [("R1", "scenario_batch")]


def test_r7_grad_host_only_barrier(tmp_path):
    """mfm_tpu.grad.engine / .report are host-only barriers (orchestration
    + atomic report IO), so their obs/IO never flags — while the grad
    DEVICE modules (grad/reverse.py etc.) are NOT on the list and a
    doctrine violation there still flags."""
    res = _lint(tmp_path, {
        "mfm_tpu/obs/instrument.py": """
            def record_scenario_batch(n, seconds):
                pass
        """,
        "mfm_tpu/grad/engine.py": """
            from mfm_tpu.obs.instrument import record_scenario_batch

            class GradEngine:
                def reverse_stress(self, portfolios):
                    record_scenario_batch(len(portfolios), 0.1)
                    return portfolios
        """,
        "mfm_tpu/grad/report.py": """
            import json
            import os

            def write_grad_report(path, report):
                with open(path, "w") as fh:
                    json.dump(report, fh)
                os.replace(path, path)
        """,
        "mfm_tpu/grad/reverse.py": """
            import numpy as np
            import jax
            import jax.numpy as jnp

            @jax.jit
            def reverse_stress_batch(x):
                return jnp.asarray(np.mean(x))   # R1: np math in traced code
        """})
    assert [(v.rule, v.qualname) for v in res.new] == \
        [("R1", "reverse_stress_batch")]


def test_r7_bare_method_over_approximation(tmp_path):
    """A bare ``.inc(...)`` in traced code resolves (over-approximately)
    against every known def — including obs metric methods — so it flags.
    That is why the metric API avoids names traced code legitimately uses
    (``set_value`` not ``set``, ``quantile_est`` not ``quantile``)."""
    res = _lint(tmp_path, {
        "mfm_tpu/obs/metrics.py": """
            class Counter:
                def inc(self, amount=1.0):
                    pass
        """,
        "mfm_tpu/model.py": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def traced(x, m):
                m.inc(1.0)          # R7: bare name matches Counter.inc
                return jnp.sum(x)
        """})
    assert [(v.rule, v.qualname) for v in res.new] == [("R7", "traced")]


def test_baseline_roundtrip_and_stale_reporting(tmp_path):
    src = {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            return jnp.arange(x.shape[0])
    """}
    dirty = _lint(tmp_path, src)
    assert len(dirty.new) == 1
    entry = {"file": dirty.new[0].file, "rule": dirty.new[0].rule,
             "qualname": dirty.new[0].qualname, "note": "fixture"}
    clean = run_lint([str(tmp_path)], baseline=[entry], root=str(tmp_path))
    assert not clean.new and len(clean.baselined) == 1 and not clean.stale

    stale_entry = dict(entry, qualname="no_such_function")
    res = run_lint([str(tmp_path)], baseline=[entry, stale_entry],
                   root=str(tmp_path))
    assert res.stale == [stale_entry]


# -- layer 3: injection drills on scratch copies of real modules --------------

def test_injected_s64_fori_bound_fails_cli(tmp_path):
    """Reverting the real eigh fix (jnp.int32 bounds -> python ints) on a
    scratch copy of the package must flip the CLI from exit 0 to exit 1.

    The whole package is copied so the conservative call graph still sees
    ``jacobi_eigh`` as traced-reachable; relative paths match, so the
    committed baseline applies to the copy unchanged."""
    shutil.copytree(REPO / "mfm_tpu", tmp_path / "mfm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    args = [str(tmp_path / "mfm_tpu"),
            "--baseline", str(REPO / DEFAULT_BASELINE),
            "--root", str(tmp_path)]
    assert main(args) == 0, "pristine scratch package should lint clean"

    eigh = tmp_path / "mfm_tpu" / "ops" / "eigh.py"
    src = eigh.read_text()
    pinned = "jnp.int32(0), jnp.int32(sweeps * (n - 1))"
    assert pinned in src, "eigh fori bounds changed — update this drill"
    eigh.write_text(src.replace(pinned, "0, sweeps * (n - 1)"))
    assert main(args) == 1
    res = run_lint([str(tmp_path / "mfm_tpu")], root=str(tmp_path))
    assert any(v.rule == "R2" and "fori_loop" in v.message for v in res.new)


def test_injected_post_donation_use_fails_cli(tmp_path):
    """Adding a use-after-donation to a scratch copy of risk_model.py must
    exit non-zero (R4) even though the pristine copy lints clean."""
    real = (REPO / "mfm_tpu" / "models" / "risk_model.py").read_text()
    scratch = tmp_path / "risk_model.py"
    scratch.write_text(real)
    base = run_lint([str(scratch)], root=str(tmp_path))
    assert not base.new, "pristine scratch copy should lint clean"

    scratch.write_text(real + textwrap.dedent("""

        def _scratch_misuse(ret, cap, styles, industry, valid, sim_covs,
                            nw_carry, vr_num, vr_den, n_industries, config):
            out = _fused_update_step(ret, cap, styles, industry, valid,
                                     sim_covs, nw_carry, vr_num, vr_den,
                                     n_industries=n_industries, config=config)
            return out, ret
    """))
    rc = main([str(scratch), "--baseline", "none", "--root", str(tmp_path)])
    assert rc == 1
    res = run_lint([str(scratch)], root=str(tmp_path))
    assert [(v.rule, v.qualname) for v in res.new] == [("R4",
                                                        "_scratch_misuse")]


def test_strict_fails_on_stale_baseline(tmp_path):
    (tmp_path / "clean.py").write_text("x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{"file": "clean.py", "rule": "R1",
                               "qualname": "ghost", "note": "stale"}]))
    args = [str(tmp_path), "--baseline", str(bl), "--root", str(tmp_path)]
    assert main(args) == 0          # default: stale is a warning
    assert main(args + ["--strict"]) == 1
