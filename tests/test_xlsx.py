"""Static-workbook ingestion (mfm_tpu/data/xlsx.py): the dependency-free
reader must handle the cell forms the reference's two shipped workbooks use
(shared strings, inline strings, cached formula strings, numbers, absent
cells) and the Wind EDB banner/header/meta/data layout.  Fixtures are
written by a minimal in-test xlsx writer — same zip+XML subset."""

import zipfile

import pytest

from mfm_tpu.data.etl import PanelStore
from mfm_tpu.data.xlsx import (
    excel_serial_to_date,
    ingest_workbooks,
    read_index_list,
    read_industry_index_prices,
    read_xlsx,
)

_WB_XML = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<workbook xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main"
 xmlns:r="http://schemas.openxmlformats.org/officeDocument/2006/relationships">
<sheets>{sheets}</sheets></workbook>"""
_RELS = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<Relationships xmlns="http://schemas.openxmlformats.org/package/2006/relationships">
{rels}</Relationships>"""


def _cell(ref, v, strings):
    if isinstance(v, str):
        if v not in strings:
            strings.append(v)
        return f'<c r="{ref}" t="s"><v>{strings.index(v)}</v></c>'
    if isinstance(v, bool):
        return f'<c r="{ref}" t="b"><v>{int(v)}</v></c>'
    return f'<c r="{ref}"><v>{v!r}</v></c>'


def write_xlsx(path, sheets):
    """sheets: list of (name, rows) — rows are lists of str/float/bool/None."""
    strings: list = []
    sheet_xml = []
    for _, rows in sheets:
        body = []
        for ri, row in enumerate(rows, 1):
            cells = [
                _cell(f"{chr(ord('A') + ci)}{ri}", v, strings)
                for ci, v in enumerate(row) if v is not None
            ]
            body.append(f'<row r="{ri}">{"".join(cells)}</row>')
        sheet_xml.append(
            '<worksheet xmlns="http://schemas.openxmlformats.org/'
            'spreadsheetml/2006/main"><sheetData>'
            + "".join(body) + "</sheetData></worksheet>")
    ss = ('<sst xmlns="http://schemas.openxmlformats.org/spreadsheetml/'
          '2006/main">' + "".join(f"<si><t>{s}</t></si>" for s in strings)
          + "</sst>")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("xl/workbook.xml", _WB_XML.format(sheets="".join(
            f'<sheet name="{n}" sheetId="{i+1}" r:id="rId{i+1}"/>'
            for i, (n, _) in enumerate(sheets))))
        z.writestr("xl/_rels/workbook.xml.rels", _RELS.format(rels="".join(
            f'<Relationship Id="rId{i+1}" Type="http://schemas.'
            f'openxmlformats.org/officeDocument/2006/relationships/'
            f'worksheet" Target="worksheets/sheet{i+1}.xml"/>'
            for i in range(len(sheets)))))
        for i, xml in enumerate(sheet_xml):
            z.writestr(f"xl/worksheets/sheet{i+1}.xml", xml)
        z.writestr("xl/sharedStrings.xml", ss)


def test_grid_reader_cell_forms(tmp_path):
    p = str(tmp_path / "t.xlsx")
    write_xlsx(p, [("S1", [["a", 1.5, True], [None, "b", None]])])
    grid = read_xlsx(p, sheet=0)
    assert grid == [["a", 1.5, True], [None, "b", None]]
    assert read_xlsx(p, sheet="S1") == grid
    with pytest.raises(ValueError, match="no sheet named"):
        read_xlsx(p, sheet="nope")


def test_excel_serial_epoch():
    assert excel_serial_to_date(38352).isoformat() == "2004-12-31"
    # the 1899-12-30 epoch bakes in the phantom 1900-02-29 (correct for
    # every post-1900-03-01 serial — all real data); pin a known modern one
    assert excel_serial_to_date(45658).isoformat() == "2025-01-01"


def test_index_list_and_edb_layout(tmp_path):
    il = str(tmp_path / "index_list.xlsx")
    write_xlsx(il, [("Sheet1", [
        ["ts_code", "name", "base_point"],
        ["000300.SH", "CSI300", 1000.0],
        ["000905.SH", "CSI500", 1000.0],
    ])])
    df = read_index_list(il)
    assert list(df.columns) == ["ts_code", "name", "base_point"]
    assert len(df) == 2

    edb = str(tmp_path / "edb.xlsx")
    rows = [
        ["Wind"],                                        # banner
        ["指标名称", "中信行业指数:计算机", "中信行业指数:银行"],  # header
        ["频率", "日", "日"],                              # meta
        ["单位", "点", "点"],
        [38352.0, 1000.0, 1000.0],
        [38356.0, 997.85, None],                         # absent cell
    ]
    write_xlsx(edb, [("中信行业指数", rows)])
    long = read_industry_index_prices(edb, sheet=0)
    assert set(long.columns) == {"index_name", "trade_date", "close"}
    assert len(long) == 3  # the absent cell drops, not zero-fills
    assert set(long.trade_date) == {"20041231", "20050104"}

    with pytest.raises(ValueError, match="指标名称"):
        read_industry_index_prices(il, sheet=0)


def test_ingest_is_idempotent(tmp_path):
    edb = str(tmp_path / "edb.xlsx")
    write_xlsx(edb, [("中信行业指数", [
        ["指标名称", "中信行业指数:计算机"],
        [38352.0, 1000.0],
        [38356.0, 997.85],
    ])])
    store = PanelStore(str(tmp_path / "store"))
    counts = ingest_workbooks(store, industry_index=edb,
                              industry_sheets=(0,))
    assert counts == {"industry_index_prices": 2}
    # re-ingest: duplicate-tolerant, nothing added
    again = ingest_workbooks(store, industry_index=edb, industry_sheets=(0,))
    assert again == {"industry_index_prices": 0}
    assert store.last_date("industry_index_prices") == "20050104"
