"""Multi-chip correctness on the virtual 8-device CPU mesh: sharded runs must
match single-device runs (XLA inserts the collectives; results identical)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.data.barra import barra_frame_to_arrays
from mfm_tpu.data.synthetic import synthetic_barra_table
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.ops.rolling import rolling_beta_hsigma
from mfm_tpu.parallel.mesh import (
    make_mesh, pad_to_mesh, panel_sharding, shard_panel, use_mesh,
)


@pytest.fixture(scope="module")
def arrays():
    df, style_names = synthetic_barra_table(T=64, N=48, P=5, Q=3, seed=9,
                                            missing=0.04)
    return barra_frame_to_arrays(df, style_names=style_names)


def _model(a, **kw):
    return RiskModel(
        jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
        jnp.asarray(a.industry), jnp.asarray(a.valid),
        n_industries=a.n_industries,
        config=RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100),
        **kw,
    )


def _assert_pipeline_sharded_equal(a, n_date, n_stock):
    rm = _model(a)
    T = rm.ret.shape[0]
    sim = jax.random.normal(jax.random.key(0), (8, rm.K, 100), jnp.float64)
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 99.0

    base = rm.run(sim_covs=sim_covs)

    mesh = make_mesh(n_date, n_stock)
    args = (rm.ret, rm.cap, rm.styles, rm.industry, rm.valid)
    # indivisible shapes pad (inertly — valid pads False) and crop back
    args = tuple(pad_to_mesh(v, mesh) for v in args)
    sharded_args = shard_panel(args, mesh)

    def pipeline(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=rm.config)
        return m.run(sim_covs=sim_covs)

    with use_mesh(mesh):
        out = jax.jit(pipeline)(*sharded_args, sim_covs)

    np.testing.assert_allclose(np.asarray(out.factor_ret)[:T],
                               np.asarray(base.factor_ret), rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out.nw_cov)[:T], np.asarray(base.nw_cov),
                               rtol=1e-8, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out.vr_cov)[:T], np.asarray(base.vr_cov),
                               rtol=1e-7, atol=1e-13, equal_nan=True)
    np.testing.assert_allclose(np.asarray(out.lamb)[:T], np.asarray(base.lamb),
                               rtol=1e-8, atol=1e-12)


def test_full_pipeline_sharded_matches_single_device(arrays):
    assert len(jax.devices()) == 8, "tests expect the 8-device virtual CPU mesh"
    _assert_pipeline_sharded_equal(arrays, 4, 2)


def test_full_pipeline_sharded_uneven_shapes():
    """Production shapes do NOT divide the mesh (CSI300's T=1,390 is not a
    multiple of 4 or 8): uneven shards (XLA pads the trailing device) must
    stay equal to the single-device run — on BOTH axes at once (T=67 on a
    4-way date axis, N=45 on a 2-way stock axis)."""
    df, style_names = synthetic_barra_table(T=67, N=45, P=5, Q=3, seed=11,
                                            missing=0.04)
    a = barra_frame_to_arrays(df, style_names=style_names)
    _assert_pipeline_sharded_equal(a, 4, 2)


def _assert_engine_sharded_equal(T, N, seed):
    """Full 16-factor engine — row-space argsort/gather/scatter included —
    stock-sharded over all 8 devices must equal the single-device run.
    pad_to_mesh is a no-op at divisible N and pads inertly (NaN = never
    listed; the int report id pads -1) at uneven N; outputs crop back.

    float64: sharding changes the reduction order of the cross-sectional
    sums (NLSIZE's per-date OLS especially), which in f32 drifts ~1e-5 —
    an arithmetic artifact, not a layout bug; f64 pins it to ~1e-13."""
    from mfm_tpu.config import FactorConfig
    from mfm_tpu.data.synthetic import (
        panel_to_engine_fields, synthetic_market_panel,
    )
    from mfm_tpu.factors.engine import FactorEngine

    data = synthetic_market_panel(T=T, N=N, n_industries=5, seed=seed)
    fields = panel_to_engine_fields(data, jnp.float64)
    idx_close = jnp.asarray(data["index_close"], jnp.float64)

    eng = FactorEngine(fields, idx_close, config=FactorConfig(), block=16)
    base = {k: np.asarray(v) for k, v in eng.run().items()}

    mesh = make_mesh(1, 8)  # all 8 devices on the stock axis
    sharding = NamedSharding(mesh, P(None, "stock"))
    sh_fields = {
        k: jax.device_put(
            pad_to_mesh(v, mesh, rolling=True,
                        fill=-1 if k == "end_date_code" else np.nan),
            sharding)
        for k, v in fields.items()
    }
    eng_sh = FactorEngine(sh_fields, idx_close, config=FactorConfig(),
                          block=16)
    with use_mesh(mesh):
        out = {k: np.asarray(v)[:, :N] for k, v in eng_sh.run().items()}

    assert set(out) == set(base)
    for k in base:
        # NLSIZE's SIZE^3-on-SIZE normal equations amplify the sharded
        # reduction-order drift to ~8e-9 relative even in f64
        np.testing.assert_allclose(out[k], base[k], rtol=1e-7, atol=1e-10,
                                   equal_nan=True, err_msg=k)


def test_factor_engine_uneven_stock_shards():
    """The row-space argsort/gather path with N % mesh != 0: 30 stocks over
    8 devices (two devices get 3, six get 4 — XLA's padded layout)."""
    _assert_engine_sharded_equal(T=70, N=30, seed=4)


def test_full_pipeline_associative_nw_sharded_matches_scan(arrays):
    """RiskModelConfig(nw_method='associative') end-to-end on a fully
    date-sharded mesh == the serial-scan single-device run.  The NW stage
    is the pipeline's only sequentially-dependent stage; the associative
    form keeps the date axis sharded through it (sequence parallelism)."""
    a = arrays
    rm = _model(a)
    sim = jax.random.normal(jax.random.key(0), (8, rm.K, 100), jnp.float64)
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 99.0
    base = rm.run(sim_covs=sim_covs)

    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100,
                          nw_method="associative")
    mesh = make_mesh(8, 1)
    args = shard_panel((rm.ret, rm.cap, rm.styles, rm.industry, rm.valid),
                       mesh)

    def pipeline(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=cfg)
        return m.run(sim_covs=sim_covs)

    with use_mesh(mesh):
        out = jax.jit(pipeline)(*args, sim_covs)

    np.testing.assert_array_equal(np.asarray(out.nw_valid),
                                  np.asarray(base.nw_valid))
    np.testing.assert_allclose(np.asarray(out.nw_cov),
                               np.asarray(base.nw_cov), rtol=1e-8, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out.vr_cov),
                               np.asarray(base.vr_cov), rtol=1e-7, atol=1e-13,
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(out.lamb), np.asarray(base.lamb),
                               rtol=1e-8, atol=1e-12)


def test_newey_west_associative_date_sharded_matches_scan():
    """The associative NW kernel directly (not through the pipeline) with its
    (T, K) input sharded across all 8 devices on the date axis.  The
    associative_scan combine must commute with the spmd partitioner's
    shard-boundary handling — covs and the validity mask both match the
    serial scan."""
    from mfm_tpu.models.newey_west import (
        newey_west_expanding, newey_west_expanding_associative,
    )

    rng = np.random.default_rng(4)
    fr = jnp.asarray(rng.normal(0, 0.01, (64, 9)))
    covs_ref, valid_ref = newey_west_expanding(fr, q=2, half_life=20.0,
                                               method="scan")

    mesh = make_mesh(8, 1)
    fr_sharded = jax.device_put(fr, NamedSharding(mesh, P("date")))
    with use_mesh(mesh):
        covs, valid = jax.jit(
            lambda r: newey_west_expanding_associative(r, q=2, half_life=20.0)
        )(fr_sharded)

    np.testing.assert_array_equal(np.asarray(valid), np.asarray(valid_ref))
    np.testing.assert_allclose(np.asarray(covs), np.asarray(covs_ref),
                               rtol=1e-9, atol=1e-15)


def test_rolling_kernel_stock_sharded(arrays):
    rng = np.random.default_rng(0)
    T, N = 80, 64
    ret = 0.01 * rng.standard_normal((T, N))
    ret[rng.random((T, N)) < 0.05] = np.nan
    mkt = 0.008 * rng.standard_normal(T)

    base_b, base_h = rolling_beta_hsigma(
        jnp.asarray(ret), jnp.asarray(mkt), window=30, half_life=10,
        min_periods=8, block=32,
    )

    mesh = make_mesh(1, 8)
    rs = panel_sharding(mesh, rolling=True)
    ret_s = jax.device_put(jnp.asarray(ret), rs)
    mkt_s = jax.device_put(jnp.asarray(mkt), NamedSharding(mesh, P()))
    b, h = jax.jit(
        lambda r, m: rolling_beta_hsigma(r, m, window=30, half_life=10,
                                         min_periods=8, block=32)
    )(ret_s, mkt_s)
    np.testing.assert_allclose(np.asarray(b), np.asarray(base_b), rtol=1e-9,
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(h), np.asarray(base_h), rtol=1e-9,
                               equal_nan=True)


def test_regression_date_and_stock_sharded_2d(arrays):
    """The 2D layout: dates over 'date', stocks over 'stock' — the stock-axis
    contractions in the normal equations become psums over the 'stock' mesh
    axis."""
    a = arrays
    rm = _model(a)
    base = rm.reg_by_time()[0]

    mesh = make_mesh(2, 4)
    args = shard_panel((rm.ret, rm.cap, rm.styles, rm.industry, rm.valid), mesh)

    def reg(ret, cap, styles, industry, valid):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=rm.config)
        return m.reg_by_time()[0]

    with use_mesh(mesh):
        out = jax.jit(reg)(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=1e-9, atol=1e-12)


def test_factor_engine_stock_sharded_matches_single_device():
    _assert_engine_sharded_equal(T=80, N=32, seed=3)


def test_portfolio_bias_sharded_matches_single_device():
    """portfolio_bias_stat under a date-sharded mesh == single device (the
    einsums contract n and k; the t axis shards cleanly)."""
    from mfm_tpu.models.bias import bias_std, portfolio_bias_stat

    rng = np.random.default_rng(5)
    T, N, K, Q = 64, 24, 6, 9
    X = jnp.asarray(rng.standard_normal((T, N, K)))
    dval = jnp.asarray(rng.random((T, N)) < 0.9)
    A = rng.standard_normal((T, K, K))
    covs = jnp.asarray(np.einsum("tik,tjk->tij", A, A) / K + np.eye(K) * 0.1)
    cov_valid = jnp.asarray(rng.random(T) < 0.85)
    spec = np.abs(rng.standard_normal((T, N))) * 0.02
    spec[rng.random((T, N)) < 0.15] = np.nan
    spec = jnp.asarray(spec)
    ret = 0.02 * rng.standard_normal((T, N))
    ret[rng.random((T, N)) < 0.1] = np.nan  # suspensions under sharding too
    ret = jnp.asarray(ret)
    weights = jnp.asarray(np.abs(rng.standard_normal((Q, N))))

    bz, bok = portfolio_bias_stat(X, dval, covs, cov_valid, spec, ret, weights)
    base = np.asarray(bias_std(bz, bok))

    mesh = make_mesh(4, 2)
    dsh = NamedSharding(mesh, P("date"))
    sharded = [jax.device_put(v, dsh)
               for v in (X, dval, covs, cov_valid, spec, ret)]

    with use_mesh(mesh):
        z, ok = jax.jit(portfolio_bias_stat)(*sharded, weights)
        got = np.asarray(bias_std(z, ok))

    np.testing.assert_array_equal(np.asarray(ok), np.asarray(bok))
    np.testing.assert_allclose(got, base, rtol=1e-9, equal_nan=True)


# ---------------------------------------------------------------------------
# PR 11: universe scaling — the bitwise contracts behind the shard-local
# panel/pjit risk stack.  Two regimes, deliberately distinguished:
#
# * the DIRECT entrypoints (run_fused on padded panels, update_guarded on a
#   stock-padded slab with replicated state) are *bitwise* equal to the
#   unsharded run at the same padded shapes — the cross-section is gathered
#   once per stage (mesh doctrine), so per-date math is identical down to
#   reduction order;
# * the PIPELINE wrapper (run_risk_pipeline(mesh=...)) additionally changes
#   whole-program fusion boundaries around the Newey-West scan, which on
#   CPU perturbs nw_cov at the ulp level (~1e-16 abs in f64) and cascades —
#   numerically irrelevant, but not bitwise; that path asserts allclose.
#
# Padded vs UNpadded is never bitwise on CPU either (array extent changes
# XLA's SIMD tiling), so every bitwise comparison here holds shapes fixed
# and varies only the sharding.
# ---------------------------------------------------------------------------


def _bitwise(tag, a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape, tag
    np.testing.assert_array_equal(a, b, err_msg=f"{tag} not bitwise")


def test_pad_to_mesh_bool_valid_pads_false():
    """Regression: a bool panel must pad with False (= never observed).
    A True pad would admit phantom stocks/dates into every masked
    cross-section reduction downstream."""
    mesh = make_mesh(2, 4)
    valid = jnp.ones((5, 6), bool)
    padded = pad_to_mesh(valid, mesh)
    assert padded.dtype == jnp.bool_
    assert padded.shape == (6, 8)
    p = np.asarray(padded)
    assert p[:5, :6].all()
    assert not p[5:, :].any() and not p[:, 6:].any()


def _uneven_universe_inputs(T, N, P, Q, seed):
    from __graft_entry__ import _synthetic_risk_inputs
    return _synthetic_risk_inputs(T, N, P, Q, seed=seed)


def test_run_fused_sharded_bitwise_uneven_n999():
    """ISSUE-11 acceptance: run_fused on a 2x4 mesh at N=999 (uneven —
    pad_to_mesh takes the stock axis to 1000) is BITWISE equal to the
    unsharded run at the same padded shapes, across all nine outputs."""
    from mfm_tpu.models.eigen import simulated_eigen_covs

    T, N, P, Q = 24, 999, 5, 3
    K = 1 + P + Q
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100)
    args = _uneven_universe_inputs(T, N, P, Q, seed=9)
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, 100, 8,
                                    jnp.float32)

    def pipeline(ret, cap, styles, industry, valid, sc):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=P, config=cfg)
        return m.run_fused(sim_covs=sc)

    mesh = make_mesh(2, 4)
    pargs = tuple(pad_to_mesh(a, mesh) for a in args)
    assert pargs[0].shape == (T, 1000)  # uneven stock axis padded

    base = jax.jit(pipeline)(*pargs, sim_covs)
    jax.block_until_ready(base)

    sargs = shard_panel(pargs, mesh)
    with use_mesh(mesh):
        out = jax.jit(pipeline)(*sargs, sim_covs)
        jax.block_until_ready(out)

    for name, b, s in zip(base._fields, base, out):
        _bitwise(f"run_fused.{name}", b, s)


def test_update_guarded_sharded_bitwise_uneven_n999():
    """The guarded daily update on a 2x4 mesh at N=999: stock axis padded
    to 1000 (state paths never pad time — padded dates would fold into the
    NW/VR carries), state replicated.  Outputs, guard report and all state
    leaves bitwise-equal to the single-device update."""
    from mfm_tpu.config import QuarantinePolicy
    from mfm_tpu.parallel.mesh import replicated

    T_HIST, SLAB, N, P, Q = 16, 4, 999, 5, 3
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100,
                          quarantine=QuarantinePolicy(enabled=True))
    mesh = make_mesh(2, 4)
    full = _uneven_universe_inputs(T_HIST + SLAB, N, P, Q, seed=9)

    def pad_stock(a):
        w = [(0, 0)] * a.ndim
        w[1] = (0, (-N) % 4)
        return jnp.pad(a, w, constant_values=False if a.dtype == bool else 0)

    fullp = tuple(pad_stock(a) for a in full)
    hist = tuple(a[:T_HIST] for a in fullp)
    slab = tuple(a[T_HIST:] for a in fullp)

    def run_pair(sharded):
        m = RiskModel(*tuple(jnp.array(a) for a in hist),
                      n_industries=P, config=cfg)
        _, state = m.init_state()
        if sharded:
            sm = shard_panel(slab, mesh)
            state = jax.device_put(state, replicated(mesh))
            with use_mesh(mesh):
                m2 = RiskModel(*tuple(jnp.array(a) for a in sm),
                               n_industries=P, config=cfg)
                outs, report, new_state = m2.update_guarded(state)
                jax.block_until_ready(outs)
        else:
            m2 = RiskModel(*tuple(jnp.array(a) for a in slab),
                           n_industries=P, config=cfg)
            outs, report, new_state = m2.update_guarded(state)
            jax.block_until_ready(outs)
        return outs, report, new_state

    b_out, b_rep, b_st = run_pair(False)
    s_out, s_rep, s_st = run_pair(True)

    for name, b, s in zip(b_out._fields, b_out, s_out):
        _bitwise(f"out.{name}", b, s)
    for name, b, s in zip(b_rep._fields, b_rep, s_rep):
        _bitwise(f"report.{name}", b, s)
    for i, (b, s) in enumerate(zip(jax.tree_util.tree_leaves(b_st),
                                   jax.tree_util.tree_leaves(s_st))):
        _bitwise(f"state.leaf{i}", b, s)


def test_guarded_update_steady_state_single_compile_under_mesh(arrays):
    """Serving invariant on the mesh: after the first guarded update
    compiles, subsequent same-shape slabs must NOT retrace (sharding
    metadata drift in the state pytree would).  <=1 lowering across two
    further updates."""
    from mfm_tpu.config import QuarantinePolicy
    from mfm_tpu.parallel.mesh import replicated
    from mfm_tpu.utils.contracts import assert_max_compiles

    a = arrays
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100,
                          quarantine=QuarantinePolicy(enabled=True))
    mesh = make_mesh(2, 4)
    panels = tuple(jnp.asarray(v) for v in
                   (a.ret, a.cap, a.styles, a.industry, a.valid))
    T_HIST, SLAB = 48, 4

    hist = tuple(p[:T_HIST] for p in panels)
    m = RiskModel(*hist, n_industries=a.n_industries, config=cfg)
    _, state = m.init_state()
    state = jax.device_put(state, replicated(mesh))

    @jax.jit
    def step(state, ret, cap, styles, industry, valid):
        m2 = RiskModel(ret, cap, styles, industry, valid,
                       n_industries=a.n_industries, config=cfg)
        return m2.update_guarded(state)

    def slab_at(t0):
        return shard_panel(tuple(p[t0:t0 + SLAB] for p in panels), mesh)

    with use_mesh(mesh):
        _, _, state = step(state, *slab_at(T_HIST))  # warmup compile
        jax.block_until_ready(state)
        with assert_max_compiles(1, "guarded update steady state on mesh"):
            _, _, state = step(state, *slab_at(T_HIST + SLAB))
            _, _, state = step(state, *slab_at(T_HIST + 2 * SLAB))
            jax.block_until_ready(state)


def _pipe_frame(T, N, P, Q, seed=0, missing=0.1):
    import pandas as pd

    rng = np.random.default_rng(seed)
    dates = pd.date_range("2020-01-01", periods=T,
                          freq="B").strftime("%Y-%m-%d")
    styles = [f"st{q}" for q in range(Q)]
    rows = []
    for t in range(T):
        for j in range(N):
            if rng.random() < missing:
                continue
            row = {"date": dates[t], "stocknames": f"s{j:03d}",
                   "capital": float(np.exp(rng.normal(10, 1))),
                   "ret": float(0.01 * rng.standard_normal()),
                   "industry": f"ind{j % P}"}
            for s in styles:
                row[s] = float(rng.standard_normal())
            rows.append(row)
    return pd.DataFrame(rows)


def test_pipeline_shard_local_mesh_matches_dense():
    """run_risk_pipeline(mesh=...) — shard-local panel construction, no
    host-side full densify — against the classic dense path.  T=37 N=21
    divides neither mesh axis, so make_array_from_callback fills the
    overhang blocks with missing data.  Allclose, not bitwise: the jit
    boundary here wraps the whole pipeline and the partitioner's fusion
    choices perturb the NW scan at the ulp level (see module comment)."""
    from mfm_tpu.config import PipelineConfig, RiskModelConfig as RMC
    from mfm_tpu.pipeline import run_risk_pipeline

    df = _pipe_frame(T=37, N=21, P=4, Q=3)
    cfg = PipelineConfig(risk=RMC(eigen_n_sims=4, eigen_sim_length=24),
                         dtype="float64")
    res_d = run_risk_pipeline(barra_df=df, config=cfg)
    mesh = make_mesh(4, 2)
    res_s = run_risk_pipeline(barra_df=df, config=cfg, mesh=mesh)

    for f in res_d.outputs._fields:
        b = np.asarray(getattr(res_d.outputs, f))
        s = np.asarray(getattr(res_s.outputs, f))
        assert b.shape == s.shape, f  # cropped back to the real (T, N)
        if b.dtype == bool:
            np.testing.assert_array_equal(b, s, err_msg=f)
        else:
            np.testing.assert_allclose(s, b, rtol=1e-9, atol=1e-12,
                                       equal_nan=True, err_msg=f)

    # the result's arrays facade is lazy: metadata came from the COO axes,
    # dense panels materialize only on access
    assert res_s.factor_returns().shape == (37, 1 + 4 + 3)
    assert res_s.specific_returns().shape == (37, 21)


def test_pipeline_mesh_state_run_requires_divisible_shapes():
    """A state (resumable-carry) run cannot be mesh-padded: padded dates
    would fold into the NW/VR carries and padded stocks into the guard
    ring.  Non-divisible shapes must raise, divisible shapes must match
    the dense state run."""
    from mfm_tpu.config import PipelineConfig, RiskModelConfig as RMC
    from mfm_tpu.pipeline import run_risk_pipeline

    df = _pipe_frame(T=36, N=21, P=4, Q=3)
    cfg = PipelineConfig(risk=RMC(eigen_n_sims=4, eigen_sim_length=24),
                         dtype="float64")

    with pytest.raises(ValueError, match="state"):
        run_risk_pipeline(barra_df=df, config=cfg, mesh=make_mesh(4, 2),
                          with_state=True)

    mesh = make_mesh(4, 1, devices=jax.devices()[:4])  # 36 % 4 == 21 % 1 == 0
    res_s = run_risk_pipeline(barra_df=df, config=cfg, mesh=mesh,
                              with_state=True)
    res_d = run_risk_pipeline(barra_df=df, config=cfg, with_state=True)
    assert res_s.state is not None
    for ls, ld in zip(jax.tree_util.tree_leaves(res_s.state),
                      jax.tree_util.tree_leaves(res_d.state)):
        a, b = np.asarray(ls), np.asarray(ld)
        if a.dtype == bool or a.dtype.kind in "iu":
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-9, atol=1e-12,
                                       equal_nan=True)
