"""Multi-chip correctness on the virtual 8-device CPU mesh: sharded runs must
match single-device runs (XLA inserts the collectives; results identical)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.data.barra import barra_frame_to_arrays
from mfm_tpu.data.synthetic import synthetic_barra_table
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.ops.rolling import rolling_beta_hsigma
from mfm_tpu.parallel.mesh import (
    make_mesh, pad_to_mesh, panel_sharding, shard_panel, use_mesh,
)


@pytest.fixture(scope="module")
def arrays():
    df, style_names = synthetic_barra_table(T=64, N=48, P=5, Q=3, seed=9,
                                            missing=0.04)
    return barra_frame_to_arrays(df, style_names=style_names)


def _model(a, **kw):
    return RiskModel(
        jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
        jnp.asarray(a.industry), jnp.asarray(a.valid),
        n_industries=a.n_industries,
        config=RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100),
        **kw,
    )


def _assert_pipeline_sharded_equal(a, n_date, n_stock):
    rm = _model(a)
    T = rm.ret.shape[0]
    sim = jax.random.normal(jax.random.key(0), (8, rm.K, 100), jnp.float64)
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 99.0

    base = rm.run(sim_covs=sim_covs)

    mesh = make_mesh(n_date, n_stock)
    args = (rm.ret, rm.cap, rm.styles, rm.industry, rm.valid)
    # indivisible shapes pad (inertly — valid pads False) and crop back
    args = tuple(pad_to_mesh(v, mesh) for v in args)
    sharded_args = shard_panel(args, mesh)

    def pipeline(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=rm.config)
        return m.run(sim_covs=sim_covs)

    with use_mesh(mesh):
        out = jax.jit(pipeline)(*sharded_args, sim_covs)

    np.testing.assert_allclose(np.asarray(out.factor_ret)[:T],
                               np.asarray(base.factor_ret), rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out.nw_cov)[:T], np.asarray(base.nw_cov),
                               rtol=1e-8, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out.vr_cov)[:T], np.asarray(base.vr_cov),
                               rtol=1e-7, atol=1e-13, equal_nan=True)
    np.testing.assert_allclose(np.asarray(out.lamb)[:T], np.asarray(base.lamb),
                               rtol=1e-8, atol=1e-12)


def test_full_pipeline_sharded_matches_single_device(arrays):
    assert len(jax.devices()) == 8, "tests expect the 8-device virtual CPU mesh"
    _assert_pipeline_sharded_equal(arrays, 4, 2)


def test_full_pipeline_sharded_uneven_shapes():
    """Production shapes do NOT divide the mesh (CSI300's T=1,390 is not a
    multiple of 4 or 8): uneven shards (XLA pads the trailing device) must
    stay equal to the single-device run — on BOTH axes at once (T=67 on a
    4-way date axis, N=45 on a 2-way stock axis)."""
    df, style_names = synthetic_barra_table(T=67, N=45, P=5, Q=3, seed=11,
                                            missing=0.04)
    a = barra_frame_to_arrays(df, style_names=style_names)
    _assert_pipeline_sharded_equal(a, 4, 2)


def _assert_engine_sharded_equal(T, N, seed):
    """Full 16-factor engine — row-space argsort/gather/scatter included —
    stock-sharded over all 8 devices must equal the single-device run.
    pad_to_mesh is a no-op at divisible N and pads inertly (NaN = never
    listed; the int report id pads -1) at uneven N; outputs crop back.

    float64: sharding changes the reduction order of the cross-sectional
    sums (NLSIZE's per-date OLS especially), which in f32 drifts ~1e-5 —
    an arithmetic artifact, not a layout bug; f64 pins it to ~1e-13."""
    from mfm_tpu.config import FactorConfig
    from mfm_tpu.data.synthetic import (
        panel_to_engine_fields, synthetic_market_panel,
    )
    from mfm_tpu.factors.engine import FactorEngine

    data = synthetic_market_panel(T=T, N=N, n_industries=5, seed=seed)
    fields = panel_to_engine_fields(data, jnp.float64)
    idx_close = jnp.asarray(data["index_close"], jnp.float64)

    eng = FactorEngine(fields, idx_close, config=FactorConfig(), block=16)
    base = {k: np.asarray(v) for k, v in eng.run().items()}

    mesh = make_mesh(1, 8)  # all 8 devices on the stock axis
    sharding = NamedSharding(mesh, P(None, "stock"))
    sh_fields = {
        k: jax.device_put(
            pad_to_mesh(v, mesh, rolling=True,
                        fill=-1 if k == "end_date_code" else np.nan),
            sharding)
        for k, v in fields.items()
    }
    eng_sh = FactorEngine(sh_fields, idx_close, config=FactorConfig(),
                          block=16)
    with use_mesh(mesh):
        out = {k: np.asarray(v)[:, :N] for k, v in eng_sh.run().items()}

    assert set(out) == set(base)
    for k in base:
        # NLSIZE's SIZE^3-on-SIZE normal equations amplify the sharded
        # reduction-order drift to ~8e-9 relative even in f64
        np.testing.assert_allclose(out[k], base[k], rtol=1e-7, atol=1e-10,
                                   equal_nan=True, err_msg=k)


def test_factor_engine_uneven_stock_shards():
    """The row-space argsort/gather path with N % mesh != 0: 30 stocks over
    8 devices (two devices get 3, six get 4 — XLA's padded layout)."""
    _assert_engine_sharded_equal(T=70, N=30, seed=4)


def test_full_pipeline_associative_nw_sharded_matches_scan(arrays):
    """RiskModelConfig(nw_method='associative') end-to-end on a fully
    date-sharded mesh == the serial-scan single-device run.  The NW stage
    is the pipeline's only sequentially-dependent stage; the associative
    form keeps the date axis sharded through it (sequence parallelism)."""
    a = arrays
    rm = _model(a)
    sim = jax.random.normal(jax.random.key(0), (8, rm.K, 100), jnp.float64)
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 99.0
    base = rm.run(sim_covs=sim_covs)

    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=100,
                          nw_method="associative")
    mesh = make_mesh(8, 1)
    args = shard_panel((rm.ret, rm.cap, rm.styles, rm.industry, rm.valid),
                       mesh)

    def pipeline(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=cfg)
        return m.run(sim_covs=sim_covs)

    with use_mesh(mesh):
        out = jax.jit(pipeline)(*args, sim_covs)

    np.testing.assert_array_equal(np.asarray(out.nw_valid),
                                  np.asarray(base.nw_valid))
    np.testing.assert_allclose(np.asarray(out.nw_cov),
                               np.asarray(base.nw_cov), rtol=1e-8, atol=1e-14)
    np.testing.assert_allclose(np.asarray(out.vr_cov),
                               np.asarray(base.vr_cov), rtol=1e-7, atol=1e-13,
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(out.lamb), np.asarray(base.lamb),
                               rtol=1e-8, atol=1e-12)


def test_newey_west_associative_date_sharded_matches_scan():
    """The associative NW kernel directly (not through the pipeline) with its
    (T, K) input sharded across all 8 devices on the date axis.  The
    associative_scan combine must commute with the spmd partitioner's
    shard-boundary handling — covs and the validity mask both match the
    serial scan."""
    from mfm_tpu.models.newey_west import (
        newey_west_expanding, newey_west_expanding_associative,
    )

    rng = np.random.default_rng(4)
    fr = jnp.asarray(rng.normal(0, 0.01, (64, 9)))
    covs_ref, valid_ref = newey_west_expanding(fr, q=2, half_life=20.0,
                                               method="scan")

    mesh = make_mesh(8, 1)
    fr_sharded = jax.device_put(fr, NamedSharding(mesh, P("date")))
    with use_mesh(mesh):
        covs, valid = jax.jit(
            lambda r: newey_west_expanding_associative(r, q=2, half_life=20.0)
        )(fr_sharded)

    np.testing.assert_array_equal(np.asarray(valid), np.asarray(valid_ref))
    np.testing.assert_allclose(np.asarray(covs), np.asarray(covs_ref),
                               rtol=1e-9, atol=1e-15)


def test_rolling_kernel_stock_sharded(arrays):
    rng = np.random.default_rng(0)
    T, N = 80, 64
    ret = 0.01 * rng.standard_normal((T, N))
    ret[rng.random((T, N)) < 0.05] = np.nan
    mkt = 0.008 * rng.standard_normal(T)

    base_b, base_h = rolling_beta_hsigma(
        jnp.asarray(ret), jnp.asarray(mkt), window=30, half_life=10,
        min_periods=8, block=32,
    )

    mesh = make_mesh(1, 8)
    rs = panel_sharding(mesh, rolling=True)
    ret_s = jax.device_put(jnp.asarray(ret), rs)
    mkt_s = jax.device_put(jnp.asarray(mkt), NamedSharding(mesh, P()))
    b, h = jax.jit(
        lambda r, m: rolling_beta_hsigma(r, m, window=30, half_life=10,
                                         min_periods=8, block=32)
    )(ret_s, mkt_s)
    np.testing.assert_allclose(np.asarray(b), np.asarray(base_b), rtol=1e-9,
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(h), np.asarray(base_h), rtol=1e-9,
                               equal_nan=True)


def test_regression_date_and_stock_sharded_2d(arrays):
    """The 2D layout: dates over 'date', stocks over 'stock' — the stock-axis
    contractions in the normal equations become psums over the 'stock' mesh
    axis."""
    a = arrays
    rm = _model(a)
    base = rm.reg_by_time()[0]

    mesh = make_mesh(2, 4)
    args = shard_panel((rm.ret, rm.cap, rm.styles, rm.industry, rm.valid), mesh)

    def reg(ret, cap, styles, industry, valid):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=a.n_industries, config=rm.config)
        return m.reg_by_time()[0]

    with use_mesh(mesh):
        out = jax.jit(reg)(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=1e-9, atol=1e-12)


def test_factor_engine_stock_sharded_matches_single_device():
    _assert_engine_sharded_equal(T=80, N=32, seed=3)


def test_portfolio_bias_sharded_matches_single_device():
    """portfolio_bias_stat under a date-sharded mesh == single device (the
    einsums contract n and k; the t axis shards cleanly)."""
    from mfm_tpu.models.bias import bias_std, portfolio_bias_stat

    rng = np.random.default_rng(5)
    T, N, K, Q = 64, 24, 6, 9
    X = jnp.asarray(rng.standard_normal((T, N, K)))
    dval = jnp.asarray(rng.random((T, N)) < 0.9)
    A = rng.standard_normal((T, K, K))
    covs = jnp.asarray(np.einsum("tik,tjk->tij", A, A) / K + np.eye(K) * 0.1)
    cov_valid = jnp.asarray(rng.random(T) < 0.85)
    spec = np.abs(rng.standard_normal((T, N))) * 0.02
    spec[rng.random((T, N)) < 0.15] = np.nan
    spec = jnp.asarray(spec)
    ret = 0.02 * rng.standard_normal((T, N))
    ret[rng.random((T, N)) < 0.1] = np.nan  # suspensions under sharding too
    ret = jnp.asarray(ret)
    weights = jnp.asarray(np.abs(rng.standard_normal((Q, N))))

    bz, bok = portfolio_bias_stat(X, dval, covs, cov_valid, spec, ret, weights)
    base = np.asarray(bias_std(bz, bok))

    mesh = make_mesh(4, 2)
    dsh = NamedSharding(mesh, P("date"))
    sharded = [jax.device_put(v, dsh)
               for v in (X, dval, covs, cov_valid, spec, ret)]

    with use_mesh(mesh):
        z, ok = jax.jit(portfolio_bias_stat)(*sharded, weights)
        got = np.asarray(bias_std(z, ok))

    np.testing.assert_array_equal(np.asarray(ok), np.asarray(bok))
    np.testing.assert_allclose(got, base, rtol=1e-9, equal_nan=True)
