"""Runtime contracts (mfm_tpu/utils/contracts.py).

``assert_max_compiles`` is the dynamic half of the doctrine: the linter
proves traced code *looks* stable; this proves a jitted step *is* reused.
The deliberately shape-polymorphic call below is the canonical failure the
guard exists to catch — each new shape retraces, the serving-latency win
evaporates, and nothing else in the suite would notice.
"""

import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.utils.contracts import (
    assert_max_compiles,
    count_compiles,
    no_tracer_leaks,
)


@jax.jit
def _double(x):
    return x * 2.0


def test_cached_signature_does_not_count():
    _double(jnp.ones(3))  # warm
    with assert_max_compiles(0, what="cache hit"):
        _double(jnp.ones(3))


def test_single_fresh_compile_is_allowed():
    # input premade: eager array creation lowers tiny programs of its own,
    # so the guarded region should contain only the step under contract
    x = jnp.ones(7)
    with assert_max_compiles(1):
        _double(x)  # fresh signature: exactly one lowering


def test_shape_polymorphic_call_is_caught():
    with pytest.raises(AssertionError, match="retraced"):
        with assert_max_compiles(1, what="polymorphic loop"):
            # one compile per distinct length — the retrace-per-day bug
            for n in (11, 12, 13):
                _double(jnp.ones(n))


def test_count_compiles_reports_exact_lowerings():
    x3, x21 = jnp.ones(3), jnp.ones(21)
    _double(x3)  # warm
    with count_compiles() as c:
        _double(x3)   # hit
        _double(x21)  # miss
    assert c.count == 1


def test_listener_is_unregistered_after_exit():
    with count_compiles() as c:
        _double(jnp.ones(31))
    seen = c.count
    _double(jnp.ones(41))  # outside the context: must not be counted
    assert c.count == seen


def test_no_tracer_leaks_catches_escape():
    leaked = []

    def leaky(x):
        leaked.append(x)
        return x * 1.0

    with pytest.raises(Exception, match="[Ll]eak"):
        with no_tracer_leaks():
            jax.jit(leaky)(jnp.ones(3))


def test_no_tracer_leaks_passes_clean_code():
    with no_tracer_leaks():
        assert float(jax.jit(lambda x: x + 1.0)(jnp.ones(3)).sum()) == 6.0
