"""Golden tests for the store -> FactorEngine-fields orchestration
(``mfm_tpu/data/prepare.py`` vs a straight pandas re-implementation of the
reference's ``load_and_prepare_data`` chain, ``load_data.py:66-418``)."""

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.data.etl import PanelStore
from mfm_tpu.data.prepare import (
    latest_index_constituents,
    load_and_prepare_data,
    prepare_factor_inputs,
    sw_l1_map,
    DAILY_FIELDS,
    FILL_FIELDS,
)
from mfm_tpu.data.synthetic import synthetic_collections


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    s = PanelStore(str(tmp_path_factory.mktemp("collections")))
    synthetic_collections(s, T=90, N=12, n_industries=4, seed=3)
    return s


def _golden_master(store, universe, index_code):
    """The reference's chain, written independently with per-stock
    ``pd.merge_asof`` (``load_data.py:41-62``) and explicit dedup sorts
    (``load_data.py:268-309``)."""
    def dt(df, cols):
        df = df.copy()
        for c in cols:
            df[c] = pd.to_datetime(df[c].astype(str), format="%Y%m%d")
        return df

    daily = store.read("daily_prices")
    daily = dt(daily[daily.ts_code.isin(universe)], ["trade_date"])
    daily = daily[["ts_code", "trade_date", *DAILY_FIELDS]]

    def two_pass(name, ann, cols):
        df = dt(store.read(name), [ann, "end_date"])
        df = df[df.ts_code.isin(universe)]
        df = df.sort_values(["ts_code", "end_date", ann],
                            ascending=[True, True, False])
        df = df.drop_duplicates(["ts_code", "end_date"], keep="first")
        df = df.sort_values(["ts_code", ann, "end_date"],
                            ascending=[True, True, False])
        df = df.drop_duplicates(["ts_code", ann], keep="first")
        return df[["ts_code", ann, "end_date", *cols]]

    bal = two_pass("balancesheet", "f_ann_date",
                   ["total_ncl", "total_hldr_eqy_inc_min_int"])
    cf = two_pass("cashflow", "f_ann_date", ["n_cashflow_act"])
    fi = dt(store.read("financial_indicators"), ["ann_date", "end_date"])
    fi = fi[fi.ts_code.isin(universe)]
    fi = fi.sort_values(["ts_code", "ann_date", "end_date"],
                        ascending=[True, True, False])
    fi = fi.drop_duplicates(["ts_code", "ann_date"], keep="first")
    fi = fi[["ts_code", "ann_date", "end_date",
             "q_profit_yoy", "q_sales_yoy", "debt_to_assets"]]

    def per_stock_asof(left, right, right_on):
        chunks = []
        for code, lg in left.groupby("ts_code", observed=True):
            rg = right[right.ts_code == code].sort_values(right_on)
            rg = rg.drop(columns=["ts_code"])
            merged = pd.merge_asof(lg.sort_values("trade_date"), rg,
                                   left_on="trade_date", right_on=right_on,
                                   direction="backward")
            chunks.append(merged)
        return pd.concat(chunks, ignore_index=True)

    m = per_stock_asof(daily, bal.rename(columns={"end_date": "ed_bal"}),
                       "f_ann_date")
    m = m.rename(columns={"f_ann_date": "balance_sheet_f_ann_date"})
    m = per_stock_asof(m, fi.rename(columns={"end_date": "ed_fi"}), "ann_date")
    m = m.rename(columns={"ann_date": "financial_indicators_ann_date"})
    m = per_stock_asof(m, cf, "f_ann_date")
    m = m.rename(columns={"f_ann_date": "cashflow_f_ann_date"})
    m = m.drop(columns=["ed_bal", "ed_fi"])

    m = m.sort_values(["ts_code", "trade_date"]).reset_index(drop=True)
    m[list(FILL_FIELDS)] = m.groupby("ts_code", observed=True)[
        list(FILL_FIELDS)].ffill()
    m[list(FILL_FIELDS)] = m[list(FILL_FIELDS)].fillna(0)
    return m


def test_universe_is_latest_snapshot(store):
    uni = latest_index_constituents(store, "000300.SH")
    assert len(uni) == 12
    assert "600012.SH" not in uni  # the outsider only in the OLD snapshot


def test_master_frame_matches_pandas_golden(store):
    uni = latest_index_constituents(store, "000300.SH")
    master, _, _ = load_and_prepare_data(store, start_date=None,
                                         fin_start_date=None)
    golden = _golden_master(store, uni, "000300.SH")

    key = ["ts_code", "trade_date"]
    master = master.sort_values(key).reset_index(drop=True)
    golden = golden.sort_values(key).reset_index(drop=True)
    assert len(master) == len(golden)
    assert (master["ts_code"].to_numpy() == golden["ts_code"].to_numpy()).all()
    assert (master["trade_date"].to_numpy()
            == golden["trade_date"].to_numpy()).all()
    for col in set(DAILY_FIELDS) | set(FILL_FIELDS):
        np.testing.assert_allclose(
            master[col].to_numpy(np.float64),
            golden[col].to_numpy(np.float64),
            rtol=1e-12, err_msg=col)
    # the surviving report period is the CASHFLOW's end_date
    # (end_date_x/_y dropped, load_data.py:383); both sides ffilled
    g_ed = golden.groupby("ts_code", observed=True)["end_date"].ffill()
    assert master["end_date"].equals(g_ed.rename("end_date"))


def test_prepared_fields_shapes_and_sentinels(store):
    prep = prepare_factor_inputs(store, start_date=None, fin_start_date=None)
    T, N = len(prep.dates), len(prep.stocks)
    assert N == 12
    for name in set(DAILY_FIELDS) | set(FILL_FIELDS):
        assert prep.fields[name].shape == (T, N)
    assert prep.fields["end_date_code"].shape == (T, N)
    assert prep.index_close.shape == (T,)
    assert np.isfinite(prep.index_close).all()

    rid = prep.fields["end_date_code"]
    close = prep.fields["close"]
    obs = np.isfinite(close)
    # report ids only on observed cells; monotone nondecreasing per stock
    assert (rid[~obs] == -1).all()
    for j in range(N):
        r = rid[obs[:, j], j]
        r = r[r >= 0]
        assert (np.diff(r) >= 0).all()
    # financial fields are never NaN on observed cells (ffill -> 0 policy)
    for col in FILL_FIELDS:
        assert np.isfinite(prep.fields[col][obs]).all(), col


def test_sw_l1_map_prefers_current_membership(store):
    sw = store.read("sw_industries")
    l1 = sw_l1_map(sw, ["600000.SH", "600001.SH"])
    # the stale is_new == 'N' rows (801990.SI) must lose
    assert not any(c == "801990.SI" for c in l1)


def test_missing_collection_raises(tmp_path):
    s = PanelStore(str(tmp_path))
    with pytest.raises(ValueError, match="index_components"):
        latest_index_constituents(s, "000300.SH")
