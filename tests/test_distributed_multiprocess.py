"""2-process jax.distributed CPU test for the multi-host helpers
(``mfm_tpu/parallel/distributed.py`` — VERDICT round-1 weak #5).

Each worker initializes the distributed runtime against a local coordinator,
builds the global ('date', 'stock') mesh with 4 virtual CPU devices per
process (8 global), checks axis placement (stock axis within one host's
devices), takes its date slice, and runs one real cross-process collective
(a psum-style global sum over a date-sharded array).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mfm_tpu.parallel.distributed import (
    initialize, make_global_mesh, process_date_slice)

pid = int(sys.argv[1])
coord = sys.argv[2]
is_multi = initialize(coordinator_address=coord, num_processes=2,
                      process_id=pid)
assert is_multi, "initialize() must report multi-host"
assert jax.process_count() == 2
assert jax.device_count() == 8 and jax.local_device_count() == 4

mesh = make_global_mesh(n_stock=2)
assert mesh.devices.shape == (4, 2)
assert mesh.axis_names == ("date", "stock")
# stock axis must stay within one host: both devices of each mesh row
# belong to the same process
rows_ok = all(len({d.process_index for d in row}) == 1
              for row in mesh.devices)

sl = process_date_slice(10)
expected = slice(0, 5) if pid == 0 else slice(5, 10)
assert sl == expected, sl

# one real cross-process collective: date-sharded global sum
sharding = NamedSharding(mesh, P("date"))
T = 8
def cb(index):
    return np.arange(T, dtype=np.float32)[index]
x = jax.make_array_from_callback((T,), sharding, cb)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
print(json.dumps({"pid": pid, "rows_ok": rows_ok,
                  "total": float(np.asarray(total))}))
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_round():
    """One two-worker round.  Returns (outs, None) or (None, failure str)."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = []
    try:
        for pid in range(2):
            # inside the try: a spawn failure on worker 1 (fork EAGAIN under
            # load) must still reap worker 0 in the finally, and is itself
            # a load symptom the retry round should ride out
            try:
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", WORKER, str(pid), coord],
                    cwd=REPO, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True,
                ))
            except OSError as e:
                return None, f"worker spawn failed: {e}"
        outs = []
        for p in procs:
            try:
                # generous: under full-suite load the gloo handshake + two
                # cold 4-device CPU backends can take minutes (flaked at
                # 180 s)
                out, err = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                return None, "distributed worker timed out"
            if p.returncode != 0:
                return None, f"worker rc={p.returncode}: {err[-4000:]}"
            # Gloo prints banners to stdout around the payload — find the
            # payload dict (a bare number in a banner also parses as JSON)
            rec = None
            for line in reversed(out.strip().splitlines()):
                try:
                    cand = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict) and "pid" in cand:
                    rec = cand
                    break
            if rec is None:
                return None, f"no JSON payload in worker stdout: {out[-2000:]}"
            outs.append(rec)
        return outs, None
    finally:
        # every failure return must reap BOTH workers: an orphaned worker
        # blocks on the 2-process barrier forever, holding 4 virtual
        # devices of load under the retry round
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.communicate()


@pytest.mark.slow
def test_two_process_mesh_and_collective():
    # One bounded retry: under full-suite load the coordinator handshake /
    # distributed init can blow jax's INTERNAL timeouts and kill a worker
    # even though nothing is wrong with the code (observed: green alone in
    # ~8 s, red inside a 21-minute saturated suite run).  A deterministic
    # breakage fails both rounds; the first failure is surfaced as a
    # warning so persistent flaking stays visible in -rw output.
    outs, fail = _run_round()
    if fail is not None:
        import warnings

        warnings.warn(f"first distributed round failed ({fail}); retrying")
        outs, fail = _run_round()
    assert fail is None, fail
    for rec in outs:
        assert rec["rows_ok"] is True
        assert rec["total"] == float(sum(range(8)))
