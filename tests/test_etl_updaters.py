"""Hermetic full-surface ETL test (VERDICT r3 missing #2): every collection
``prepare_factor_inputs`` reads is populated exclusively through
``IncrementalUpdater`` methods against a fake source (no direct store
inserts), then the one-command ``pipeline`` CLI runs off that store.

Reference scope: ``update_mongo_db.py:579-614`` (the ``__main__`` chain:
stock_info -> daily_prices -> statements -> index daily prices -> index
components -> SW industries) plus the three updaters the repo previously
lacked (``update_stock_info`` ``:32-57``, ``update_daily_index_prices``
``:387-454``, ``update_sw_industries_from_csv`` ``:536-576``).
"""

import json

import pandas as pd
import pytest

from mfm_tpu.cli import main as cli_main
from mfm_tpu.data.etl import IncrementalUpdater, PanelStore
from mfm_tpu.data.prepare import prepare_factor_inputs
from mfm_tpu.data.synthetic import synthetic_collections

COLLECTIONS = ("stock_info", "daily_prices", "balancesheet", "cashflow",
               "financial_indicators", "index_daily_prices",
               "index_components", "sw_industries")


class FullFakeSource:
    """Serves the synthetic truth frames through the tushare fetch surface."""

    def __init__(self, truth, dates):
        self.t = truth
        self.dates = dates

    def fetch_stock_info(self):
        return self.t["stock_info"].copy()

    def fetch_trade_calendar(self, start_date, end_date):
        return [d for d in self.dates if start_date <= d <= end_date]

    def fetch_daily_prices(self, trade_date):
        df = self.t["daily_prices"]
        return df[df["trade_date"] == trade_date].copy()

    def _stmt(self, name, ts_code):
        # the real API's start/end filter announcement dates; serving the
        # stock's full history keeps the fixture simple and is a superset
        df = self.t[name]
        return df[df["ts_code"] == ts_code].copy()

    def fetch_balancesheet_by_stock(self, ts_code, start_date=None,
                                    end_date=None):
        return self._stmt("balancesheet", ts_code)

    def fetch_cashflow_by_stock(self, ts_code, start_date=None, end_date=None):
        return self._stmt("cashflow", ts_code)

    def fetch_income_by_stock(self, ts_code, start_date=None, end_date=None):
        # the income collection exists in the reference DB but is unused by
        # the factor pipeline; empty is a valid fetch result
        return pd.DataFrame(columns=["ts_code", "end_date", "f_ann_date"])

    def fetch_financial_indicators_by_stock(self, ts_code, start_date=None,
                                            end_date=None):
        return self._stmt("financial_indicators", ts_code)

    def fetch_daily_index_prices(self, ts_code, start_date=None,
                                 end_date=None):
        df = self.t["index_daily_prices"]
        df = df[df["ts_code"] == ts_code]
        if start_date is not None:
            df = df[df["trade_date"] >= start_date]
        if end_date is not None:
            df = df[df["trade_date"] <= end_date]
        return df.copy()

    def fetch_index_components(self, index_code, trade_date):
        df = self.t["index_components"]
        return df[(df["index_code"] == index_code)
                  & (df["trade_date"] == trade_date)].copy()

    def fetch_sw_industries(self, ts_code):
        df = self.t["sw_industries"]
        return df[df["ts_code"] == ts_code].copy()


@pytest.fixture(scope="module")
def truth(tmp_path_factory):
    d = tmp_path_factory.mktemp("truth")
    s = PanelStore(str(d))
    meta = synthetic_collections(s, T=100, N=16, n_industries=4, seed=7)
    return {n: s.read(n) for n in COLLECTIONS}, meta


def test_run_all_populates_every_prepare_collection(truth, tmp_path, capsys):
    frames, meta = truth
    src = FullFakeSource(frames, list(meta["dates"]))
    store_dir = str(tmp_path / "store")
    store = PanelStore(store_dir)
    up = IncrementalUpdater(store=store, source=src, sleep=lambda s: None)
    start, end = meta["dates"][0], meta["dates"][-1]

    summary = up.run_all(start, end, index_codes=(meta["index_code"],),
                         components_date=meta["dates"][-1])

    assert summary["stock_info"] == len(frames["stock_info"])
    assert summary["daily_prices"] == len(frames["daily_prices"])
    assert summary["index_daily_prices"] == len(frames["index_daily_prices"])
    assert summary["sw_industries"] == len(frames["sw_industries"])
    assert summary["statements"]["balancesheet"] == len(frames["balancesheet"])
    assert summary["statements"]["cashflow"] == len(frames["cashflow"])
    assert summary["statements"]["financial_indicators"] == \
        len(frames["financial_indicators"])
    assert summary["statements"]["income"] == 0  # empty fetch is fine

    for name in COLLECTIONS:
        if name == "index_components":
            continue  # only the components_date snapshot is refreshed
        got = store.read(name)
        assert len(got), name

    # watermark/dedup idempotence: a second chained run refetches nothing
    summary2 = up.run_all(start, end, index_codes=(meta["index_code"],))
    assert summary2["daily_prices"] == 0
    assert summary2["index_daily_prices"] == 0
    assert summary2["statements"]["balancesheet"] == 0
    assert summary2["statements"]["financial_indicators"] == 0

    # the full prepare path reads only updater-written collections
    prep = prepare_factor_inputs(store, index_code=meta["index_code"],
                                 start_date=start, fin_start_date=None)
    assert prep.fields["close"].shape[1] == 16
    assert prep.index_close.shape[0] == prep.fields["close"].shape[0]

    # ... and the one-command pipeline runs end-to-end off that store
    out = str(tmp_path / "results")
    cli_main(["pipeline", "--store", store_dir, "--out", out,
              "--eigen-sims", "8", "--start", start])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["stocks"] == 16
    assert rec["rows"] > 0


def test_sw_industries_from_csv(truth, tmp_path):
    """The reference's CSV refresh path (``update_mongo_db.py:536-576``)."""
    frames, _ = truth
    csv = tmp_path / "sw.csv"
    frames["sw_industries"].to_csv(csv, index=False)
    store = PanelStore(str(tmp_path / "store"))
    up = IncrementalUpdater(store=store, source=object(),
                            sleep=lambda s: None)
    n = up.update_sw_industries(csv_path=str(csv))
    assert n == len(frames["sw_industries"])
    # full-refresh semantics: a second load replaces, not appends
    assert up.update_sw_industries(csv_path=str(csv)) == n
    assert len(store.read("sw_industries")) == n


def test_etl_update_cli(truth, tmp_path, capsys, monkeypatch):
    frames, meta = truth
    src = FullFakeSource(frames, list(meta["dates"]))
    import mfm_tpu.data.tushare_source as ts_mod
    monkeypatch.setattr(ts_mod, "TushareSource", lambda token=None: src)
    store_dir = str(tmp_path / "store")
    cli_main(["etl-update", "--store", store_dir,
              "--start", meta["dates"][0], "--end", meta["dates"][-1],
              "--index-codes", meta["index_code"],
              "--components-date", meta["dates"][-1]])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["daily_prices"] == len(frames["daily_prices"])
    assert rec["index_daily_prices"] == len(frames["index_daily_prices"])
    assert PanelStore(store_dir).distinct_count(
        "index_components", "con_code") == 16


def test_index_watermark_is_per_index(truth, tmp_path):
    """An index code added AFTER the first refresh must get its full
    history (the reference's single collection-level watermark would skip
    it, update_mongo_db.py:398 — documented deviation)."""
    frames, meta = truth
    two = frames["index_daily_prices"].copy()
    other = two.assign(ts_code="000016.SH")
    t = dict(frames)
    t["index_daily_prices"] = pd.concat([two, other], ignore_index=True)
    src = FullFakeSource(t, list(meta["dates"]))
    store = PanelStore(str(tmp_path / "store"))
    up = IncrementalUpdater(store=store, source=src, sleep=lambda s: None)
    end = meta["dates"][-1]

    assert up.update_daily_index_prices([meta["index_code"]],
                                        end_date=end) == len(two)
    # second run adds a brand-new code: full backfill, no refetch of the old
    n = up.update_daily_index_prices([meta["index_code"], "000016.SH"],
                                     end_date=end)
    assert n == len(other)
    got = store.read("index_daily_prices")
    assert got["ts_code"].nunique() == 2
    assert len(got) == len(two) + len(other)
    # and now everything is a no-op
    assert up.update_daily_index_prices([meta["index_code"], "000016.SH"],
                                        end_date=end) == 0


def test_repair_missing_stocks_refetches(truth, tmp_path, capsys,
                                         monkeypatch):
    """The repair tool must detect AND refill gaps (fill_missing_data.py:
    16-64): per-stock ranged refetch, duplicate-tolerant insert."""
    frames, meta = truth
    daily = frames["daily_prices"]
    gone = meta["stocks"][0]
    src = FullFakeSource(dict(frames), list(meta["dates"]))

    # per-stock fetch surface for the repair path
    def by_stock(ts_code, start_date=None, end_date=None):
        df = daily[daily["ts_code"] == ts_code]
        if start_date is not None:
            df = df[df["trade_date"] >= start_date]
        if end_date is not None:
            df = df[df["trade_date"] <= end_date]
        return df.copy()

    src.fetch_daily_prices_by_stock = by_stock

    store = PanelStore(str(tmp_path / "store"))
    store.insert("stock_info", frames["stock_info"], unique=("ts_code",))
    store.insert("daily_prices", daily[daily["ts_code"] != gone],
                 unique=("ts_code", "trade_date"))

    up = IncrementalUpdater(store=store, source=src, sleep=lambda s: None)
    rep = up.repair_missing_stocks(meta["dates"][0], meta["dates"][-1])
    # the outsider stock (not in index, but in stock_info) is also refetched
    assert gone in rep["missing"]
    assert rep["rows_inserted"] == sum(
        len(daily[daily["ts_code"] == c]) for c in rep["missing"])
    got = store.read("daily_prices")
    assert set(got["ts_code"]) == set(daily["ts_code"])
    # idempotent: nothing left to repair
    rep2 = up.repair_missing_stocks(meta["dates"][0], meta["dates"][-1])
    assert rep2["missing"] == [] and rep2["rows_inserted"] == 0

    # the CLI --fix path drives the same repair
    import mfm_tpu.data.tushare_source as ts_mod
    store2_dir = str(tmp_path / "store2")
    store2 = PanelStore(store2_dir)
    store2.insert("stock_info", frames["stock_info"], unique=("ts_code",))
    store2.insert("daily_prices", daily[daily["ts_code"] != gone],
                  unique=("ts_code", "trade_date"))
    monkeypatch.setattr(ts_mod, "TushareSource", lambda token=None: src)
    cli_main(["etl-missing", "--store", store2_dir, "--fix",
              "--start", meta["dates"][0], "--end", meta["dates"][-1]])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["rows_inserted"] > 0
    assert gone in rec["missing"]


def test_etl_missing_fix_rejects_custom_collection(tmp_path):
    with pytest.raises(SystemExit, match="daily_prices"):
        cli_main(["etl-missing", "--store", str(tmp_path), "--fix",
                  "--name", "balancesheet", "--start", "20200101"])


def test_plan_update_watermarks_and_dry_run_cli(tmp_path, capsys):
    """plan_update reports watermark-derived fetch ranges with zero source
    calls, and `etl-update --dry-run` needs no token at all."""
    import json

    import pandas as pd

    from mfm_tpu.cli import main
    from mfm_tpu.data.etl import PanelStore, plan_update

    store = PanelStore(str(tmp_path / "store"))
    store.insert("stock_info", pd.DataFrame({"ts_code": ["a", "b", "c"]}))
    store.insert("daily_prices", pd.DataFrame({
        "ts_code": ["a"], "trade_date": ["20240105"], "close": [1.0]}))
    store.insert("index_daily_prices", pd.DataFrame({
        "ts_code": ["000300.SH"], "trade_date": ["20240110"],
        "close": [3000.0]}))

    plan = plan_update(store, "20240101", "20240108",
                       index_codes=["000300.SH", "000016.SH"])
    assert plan["daily_prices"]["watermark"] == "20240105"
    assert plan["daily_prices"]["fetch_from"] == "20240106"
    assert plan["daily_prices"]["up_to_date"] is False
    assert plan["statements"]["balancesheet"]["per_stock_calls"] == 3
    idx = plan["index_daily_prices"]
    assert idx["000300.SH"]["up_to_date"] is True   # wm past end_date
    assert idx["000016.SH"]["watermark"] is None    # never fetched
    assert plan["stock_info"] == {"rows": 3, "action": "full refresh"}

    main(["etl-update", "--store", str(tmp_path / "store"),
          "--start", "20240101", "--end", "20240108", "--dry-run"])
    rec = json.loads(capsys.readouterr().out)
    assert rec["daily_prices"]["fetch_from"] == "20240106"


def test_plan_update_clamps_and_mirrors_toggles(tmp_path, capsys):
    import json

    import pandas as pd

    from mfm_tpu.cli import main
    from mfm_tpu.data.etl import PanelStore, plan_update

    store = PanelStore(str(tmp_path / "s"))
    # stale watermark far before start: the real run never backfills
    # pre-start days, so the plan must clamp fetch_from to start
    store.insert("daily_prices", pd.DataFrame({
        "ts_code": ["a"], "trade_date": ["20230105"], "close": [1.0]}))
    plan = plan_update(store, "20240101", "20240131")
    assert plan["daily_prices"]["fetch_from"] == "20240101"

    # empty store: statement call counts are unknown, not zero
    assert plan["statements"]["income"]["per_stock_calls"] is None
    assert "universe unknown" in plan["statements"]["income"]["note"]

    # step toggles mirror run_all's
    assert "index_components" not in plan and "sw_industries" in plan
    plan2 = plan_update(store, "20240101", "20240131",
                        components_date="20240131", sw=False)
    assert plan2["index_components"]["date"] == "20240131"
    assert "sw_industries" not in plan2

    main(["etl-update", "--store", str(tmp_path / "s"),
          "--start", "20240101", "--end", "20240131", "--no-sw",
          "--components-date", "20240131", "--dry-run"])
    rec = json.loads(capsys.readouterr().out)
    assert "sw_industries" not in rec and "index_components" in rec
