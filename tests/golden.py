"""Golden NumPy/pandas implementations of the reference's numerical contracts.

Written independently from the contract descriptions in SURVEY.md §2.3/§3 —
deliberately plain, loopy, per-window/per-date NumPy so that agreement with
the batched JAX kernels is meaningful.  statsmodels is not available in this
image, so its exact math is reproduced inline where the reference calls it:
``sm.WLS(y, X, weights=w).fit()`` solves the whitened least squares
``lstsq(sqrt(w) X, sqrt(w) y)`` with ``model.scale = sum(w e^2)/(n - p)``,
and ``sm.OLS`` is the w=1 special case (statsmodels regression docs; the
reference call sites are ``factor_calculator.py:99-102`` and
``post_processing.py:60``).
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def wls_fit(y, X, w=None):
    """(params, scale): exact statsmodels WLS semantics, pure NumPy."""
    y = np.asarray(y, float)
    X = np.asarray(X, float)
    n, p = X.shape
    w = np.ones(n) if w is None else np.asarray(w, float)
    sw = np.sqrt(w)
    params, *_ = np.linalg.lstsq(X * sw[:, None], y * sw, rcond=None)
    e = y - X @ params
    scale = np.sum(w * e * e) / (n - p)
    return params, scale


def add_constant(X):
    X = np.asarray(X, float)
    if X.ndim == 1:
        X = X[:, None]
    return np.hstack([np.ones((X.shape[0], 1)), X])


# ---------------------------------------------------------------------------
# cross-sectional WLS (contract: Barra-master/mfm/CrossSection.py)
# ---------------------------------------------------------------------------

def golden_cross_section(ret, cap, styles, ind_onehot):
    """One date, valid rows only. ret (n,), cap (n,), styles (n, Q),
    ind_onehot (n, P). Returns (factor_ret (K,), specific (n,), r2)."""
    n, Q = styles.shape
    P = ind_onehot.shape[1]
    wmu = np.sum(styles * cap[:, None], axis=0) / np.sum(cap)
    sd = np.std(styles, axis=0)  # equal-weight population std
    sty = (styles - wmu) / sd
    X = np.hstack([np.ones((n, 1)), ind_onehot, sty])
    w = np.sqrt(cap) / np.sum(np.sqrt(cap))
    W = np.diag(w)
    K = 1 + P + Q
    if P > 0:
        ind_cap = ind_onehot.T @ cap
        R = np.eye(K)
        R[P, 1 : 1 + P] = -ind_cap / ind_cap[-1]
        R = np.delete(R, P, axis=1)
        Xr = X @ R
        omega = R @ np.linalg.pinv(Xr.T @ W @ Xr) @ Xr.T @ W
    else:
        omega = np.linalg.pinv(X.T @ W @ X) @ X.T @ W
    f = omega @ ret
    spec = ret - X @ f
    r2 = 1.0 - np.var(spec) / np.var(ret)
    return f, spec, r2


def golden_reg_by_time(df, style_names, industry_codes):
    """Serial per-date loop over a barra-format long frame (drop-any-NaN rows
    already applied). Returns dict keyed by date."""
    out = {}
    for date, g in df.groupby("date"):
        g = g.sort_values("stocknames")
        ind_oh = np.stack(
            [(g["industry"] == c).to_numpy(float) for c in industry_codes], axis=1
        )
        f, spec, r2 = golden_cross_section(
            g["ret"].to_numpy(),
            g["capital"].to_numpy(),
            g[style_names].to_numpy(),
            ind_oh,
        )
        out[date] = dict(f=f, spec=spec, r2=r2, stocks=g["stocknames"].to_numpy())
    return out


# ---------------------------------------------------------------------------
# Newey-West (contract: Barra-master/mfm/utils.py:16-50)
# ---------------------------------------------------------------------------

def golden_newey_west(ret: np.ndarray, q=2, tao=252.0):
    T, K = ret.shape
    if T <= q or T <= K:
        raise ValueError("T <= q or T <= K")
    w = 0.5 ** (np.arange(T - 1, -1, -1) / tao)
    w = w / w.sum()
    d = ret - (w[:, None] * ret).sum(axis=0)
    V = np.zeros((K, K))
    for t in range(T):
        V += w[t] * np.outer(d[t], d[t])
    for lag in range(1, q + 1):
        G = np.zeros((K, K))
        for t in range(T - lag):
            G += w[lag + t] * np.outer(d[t], d[t + lag])
        V += (1 - lag / (1 + q)) * (G + G.T)
    return V


# ---------------------------------------------------------------------------
# eigenfactor risk adjustment (contract: utils.py:55-92), draws injected
# ---------------------------------------------------------------------------

def golden_eigen_adj(cov, draws, scale_coef=1.4):
    """draws: (M, K, T_sim) standard normal. Scaling convention
    b_m = sqrt(D0) * N_m (distribution identical to the reference's
    multivariate_normal(0, diag(D0))).  U0 signs canonicalized (largest
    component positive) to match the framework's convention — the adjusted
    covariance depends on the draw<->eigenpair pairing, so golden and
    implementation must fix the same basis."""
    D0, U0 = np.linalg.eigh(cov)
    lead = np.take_along_axis(U0, np.argmax(np.abs(U0), axis=0)[None, :], axis=0)
    U0 = U0 * np.where(lead < 0, -1.0, 1.0)
    v = []
    for Nm in draws:
        bm = np.sqrt(np.maximum(D0, 0))[:, None] * Nm
        fm = U0 @ bm
        Fm = np.cov(fm)
        Dm, Um = np.linalg.eigh(Fm)
        Dm_hat = np.diagonal(Um.T @ cov @ Um)
        v.append(Dm_hat / Dm)
    v = np.sqrt(np.mean(np.array(v), axis=0))
    v = scale_coef * (v - 1) + 1
    return (U0 * (v**2 * D0)[None, :]) @ U0.T


# ---------------------------------------------------------------------------
# vol regime adjustment (contract: MFM.py:130-167)
# ---------------------------------------------------------------------------

def golden_vol_regime(factor_ret, factor_var, tao=42.0):
    """factor_ret (T, K); factor_var (T, K) with NaN rows for invalid dates.
    Returns lamb (T,)."""
    T = factor_ret.shape[0]
    B = np.sqrt(np.mean(factor_ret**2 / factor_var, axis=1))
    weights = 0.5 ** (np.arange(T - 1, -1, -1) / tao)
    lamb = []
    for t in range(1, T + 1):
        okidx = np.isnan(factor_var[:t]).sum(axis=1) == 0
        wsel = weights[:t][okidx]
        if wsel.sum() == 0:
            lamb.append(0.0)
            continue
        okw = wsel / wsel.sum()
        lamb.append(np.sqrt(np.sum(okw * B[:t][okidx] ** 2)))
    return np.array(lamb)


# ---------------------------------------------------------------------------
# rolling factors (contracts: Barra_factor_cal/factor_calculator.py)
# ---------------------------------------------------------------------------

def golden_beta_hsigma(ret: pd.Series, market: pd.Series, T=252, hl=63, minp=42):
    """Per-stock rolling WLS via statsmodels, exactly the reference's recipe
    (factor_calculator.py:86-122)."""
    decay = 0.5 ** (1 / hl)
    weights = decay ** np.arange(T - 1, -1, -1)
    frame = pd.DataFrame({"ret": ret.values, "market_ret": market.values})
    betas, hsigmas = [], []
    for w in frame.rolling(window=T, min_periods=1):
        d = w.dropna()
        if d.shape[0] < minp:
            betas.append(np.nan)
            hsigmas.append(np.nan)
            continue
        params, scale = wls_fit(
            d["ret"].to_numpy(),
            add_constant(d["market_ret"].to_numpy()),
            weights[-d.shape[0]:],
        )
        betas.append(params[1])
        hsigmas.append(np.sqrt(scale))
    return np.array(betas), np.array(hsigmas)


def golden_rstr(log_ret: pd.Series, T=504, L=21, hl=126, minp=42):
    W = T - L
    decay = 0.5 ** (1 / hl)
    weights = decay ** np.arange(0, W)

    def calc(window_s):
        ws = pd.Series(weights[: len(window_s)], index=window_s.index)
        valid = window_s.dropna()
        if len(valid) < minp:
            return np.nan
        vw = ws.loc[valid.index]
        return float(np.sum(valid * (vw / vw.sum())))

    return (
        log_ret.shift(L)
        .rolling(window=W, min_periods=minp)
        .apply(calc, raw=False)
        .to_numpy()
    )


def golden_dastd(excess: pd.Series, T=252, hl=42, minp=42):
    decay = 0.5 ** (1 / hl)
    weights = decay ** np.arange(T - 1, -1, -1)

    def calc(window_s):
        valid = window_s.dropna()
        if len(valid) < minp:
            return np.nan
        ws = pd.Series(weights[-len(valid):], index=valid.index)
        nw = ws / ws.sum()
        mu = float(np.sum(valid * nw))
        return float(np.sqrt(np.sum(nw * (valid - mu) ** 2)))

    return excess.rolling(window=T, min_periods=minp).apply(calc, raw=False).to_numpy()


def golden_cmra(log_ret: pd.Series, T=252):
    def calc(window_s):
        if window_s.shape[0] < T:
            return np.nan
        z = np.exp(window_s.cumsum()) - 1
        return float(np.log(1 + z.max()) - np.log(1 + z.min()))

    return log_ret.rolling(window=T).apply(calc, raw=False).to_numpy()


def golden_liquidity(turnover_pct: pd.Series):
    dtv = turnover_pct / 100.0
    out = {}
    for name, (w, mp) in {
        "STOM": (21, 15), "STOQ": (63, 42), "STOA": (252, 126),
    }.items():
        base = dtv.rolling(window=w, min_periods=mp).sum()
        out[name] = np.log(base.replace(0, np.nan)).to_numpy()
    return out


def golden_winsorize(df, cols, n_std=2.5):
    out = df.copy()
    f = lambda x: x.clip(lower=x.mean() - n_std * x.std(), upper=x.mean() + n_std * x.std())
    for c in cols:
        out[c] = out.groupby("trade_date")[c].transform(f)
    return out


def golden_composite(df, components, weights):
    num = pd.Series(0.0, index=df.index)
    den = pd.Series(0.0, index=df.index)
    for comp, w in zip(components, weights):
        num += df[comp].fillna(0) * w
        den += df[comp].notna() * w
    return (num / den).to_numpy()


def golden_ortho(df, target, regressors):
    def reg(g):
        y = g[target]
        X = g[list(regressors)]
        valid = pd.concat([y, X], axis=1).dropna().index
        if len(valid) < len(regressors) + 2:
            return pd.Series(np.nan, index=g.index)
        params, _ = wls_fit(
            y.loc[valid].to_numpy(), add_constant(X.loc[valid].to_numpy())
        )
        resid = y.loc[valid].to_numpy() - add_constant(X.loc[valid].to_numpy()) @ params
        return pd.Series(resid, index=valid).reindex(g.index)

    res = df.groupby("trade_date", group_keys=False).apply(reg, include_groups=False)
    return res.to_numpy()


def golden_nlsize(df):
    """Per-date OLS of SIZE^3 on SIZE; NLSIZE = -resid
    (factor_calculator.py:252-275)."""
    def reg(g):
        v = g[["SIZE"]].dropna()
        if v.shape[0] < 2:
            return pd.Series(np.nan, index=g.index)
        X = add_constant(v["SIZE"].to_numpy())
        y = v["SIZE"].to_numpy() ** 3
        params, _ = wls_fit(y, X)
        return pd.Series(-(y - X @ params), index=v.index).reindex(g.index)

    return df.groupby("trade_date", group_keys=False).apply(reg, include_groups=False).to_numpy()
