"""Test env: CPU backend with 8 virtual devices (the multi-chip stand-in,
SURVEY.md §4) and float64 enabled for 1e-8-level parity with the NumPy/pandas
golden implementations."""

import os

# force CPU: the session env points JAX_PLATFORMS at the real TPU (axon),
# but parity tests need float64 and 8 virtual devices
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

import jax  # noqa: E402  (import after env setup)

# the session env pins JAX_PLATFORMS=axon before pytest starts, and that
# wins over os.environ changes made here — override through the config API
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
