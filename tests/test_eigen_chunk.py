"""The chunked eigen Monte-Carlo stream and the fused risk step.

Contract under test (models/eigen.py): ``eigen_risk_adjust_by_time`` with
any ``chunk`` setting — including chunk sizes that do not divide T, and an
"auto"-resolved one — produces results identical to the full-batch path,
because both run the same per-date op sequence and the solver dispatch is
pinned chunk-invariant via ``batch_hint``.  Likewise ``RiskModel.run_fused``
is the same four-stage math as ``run`` inside one jitted program, and the
CPU Jacobi fallback (ops/eigh.py, ``cpu_jacobi=True``) agrees with LAPACK.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.models.eigen import (
    auto_eigen_chunk,
    eigen_risk_adjust_by_time,
    simulated_eigen_covs,
)
from mfm_tpu.models.risk_model import RiskModel


def _cov_panel(T=37, K=8, M=12, seed=0, invalid_frac=0.15):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(T, 60, K))
    covs = jnp.asarray(np.einsum("tnk,tnl->tkl", A, A) / 59.0)
    valid = jnp.asarray(rng.random(T) > invalid_frac)
    sim_covs = simulated_eigen_covs(jax.random.key(1), K, 100, M,
                                    dtype=covs.dtype)
    return covs, valid, sim_covs


@functools.partial(jax.jit, static_argnames="chunk")
def _adjust(covs, valid, sim_covs, chunk):
    return eigen_risk_adjust_by_time(covs, valid, sim_covs, sim_length=100,
                                     chunk=chunk)


# 1 (degenerate slabs), 7 (37 % 7 != 0: exercises the padded tail), T
# (exactly one slab), 64 (> T: must take the full-batch path)
@pytest.mark.parametrize("chunk", [1, 7, 37, 64])
def test_chunked_equals_full_batch_bitwise(chunk):
    covs, valid, sim_covs = _cov_panel()
    ref, ok_ref = _adjust(covs, valid, sim_covs, None)
    out, ok = _adjust(covs, valid, sim_covs, chunk)
    assert jnp.array_equal(ok, ok_ref)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_chunked_all_invalid_panel():
    # every date invalid: the eigh runs on identity stand-ins, the output
    # must be all-NaN/invalid — including inside padded slabs
    covs, _, sim_covs = _cov_panel()
    valid = jnp.zeros(covs.shape[0], bool)
    out, ok = _adjust(covs, valid, sim_covs, 7)
    assert not bool(ok.any())
    assert bool(jnp.isnan(out).all())


def test_auto_chunk_policy_shapes():
    # tiny problem fits any budget -> full batch; absurdly large T must
    # chunk, and the chunk must be a valid size
    assert auto_eigen_chunk(16, 4, 8, itemsize=4) is None
    c = auto_eigen_chunk(10**9, 100, 42, itemsize=4)
    assert isinstance(c, int) and 1 <= c < 10**9


def test_auto_chunk_matches_full_batch():
    covs, valid, sim_covs = _cov_panel()
    cfgs = [RiskModelConfig(eigen_chunk=ec, eigen_n_sims=sim_covs.shape[0])
            for ec in ("auto", None, 7)]
    outs = []
    for cfg in cfgs:
        rm = RiskModel(jnp.zeros((covs.shape[0], 4)),  # panels unused here
                       jnp.ones((covs.shape[0], 4)),
                       jnp.zeros((covs.shape[0], 4, 1)),
                       jnp.zeros((covs.shape[0], 4), int),
                       jnp.ones((covs.shape[0], 4), bool),
                       n_industries=2, config=cfg)
        outs.append(rm.eigen_risk_adj_by_time(
            covs, valid, sim_covs=sim_covs, sim_length=100))
    for out, ok in outs[1:]:
        # eager stage dispatch: same math, compiled per chunk setting —
        # f64 keeps any fusion-order difference at the noise floor
        np.testing.assert_allclose(np.asarray(out), np.asarray(outs[0][0]),
                                   rtol=1e-12, atol=1e-12)
        assert jnp.array_equal(ok, outs[0][1])


def test_auto_chunk_uses_mc_dtype_itemsize(monkeypatch):
    # under eigen_mc_dtype the streamed G transient is assembled in the MC
    # dtype, so "auto" must size the chunk from ITS itemsize (2 for bf16),
    # not the compute dtype's — bf16 halves the per-date footprint, doubling
    # the chunk.  Pin the headroom so the resolution is deterministic:
    # budget = min(64MiB // 4, host cap) = 16MiB; per-date transient at
    # M=64, K=32 is 64*32*32*itemsize*workspace_factor -> 1MiB (f32) /
    # 0.5MiB (bf16), and T=64 dates overflow the budget either way.
    from mfm_tpu.models import eigen as eigen_mod

    monkeypatch.setattr(eigen_mod, "_memory_headroom_bytes",
                        lambda backend: 64 * 1024 ** 2)
    T, M = 64, 64
    panels = (jnp.zeros((T, 4)), jnp.ones((T, 4)), jnp.zeros((T, 4, 3)),
              jnp.zeros((T, 4), int), jnp.ones((T, 4), bool))
    chunks = {}
    for mc_dtype in (None, "bfloat16"):
        cfg = RiskModelConfig(eigen_chunk="auto", eigen_n_sims=M,
                              eigen_mc_dtype=mc_dtype)
        rm = RiskModel(*panels, n_industries=28, config=cfg)  # K = 32
        assert rm.K == 32
        chunks[mc_dtype] = rm._resolve_eigen_chunk(M, itemsize=4)
    assert chunks[None] == 16
    assert chunks["bfloat16"] == 32
    # the explicit-int and full-batch settings must ignore the MC dtype
    cfg = RiskModelConfig(eigen_chunk=7, eigen_n_sims=M,
                          eigen_mc_dtype="bfloat16")
    rm = RiskModel(*panels, n_industries=28, config=cfg)
    assert rm._resolve_eigen_chunk(M, itemsize=4) == 7


def test_eigen_chunk_config_validation():
    for bad in (0, -3, True, 1.5, "sometimes"):
        with pytest.raises((ValueError, TypeError)):
            RiskModelConfig(eigen_chunk=bad)
    for good in (None, "auto", 1, 64):
        RiskModelConfig(eigen_chunk=good)


def _risk_panel(T=48, N=24, P=4, Q=3, seed=0):
    rng = np.random.default_rng(seed)
    ret = jnp.asarray(rng.normal(0, 0.02, (T, N)))
    cap = jnp.asarray(rng.lognormal(10, 1, (T, N)))
    styles = jnp.asarray(rng.normal(size=(T, N, Q)))
    industry = jnp.asarray(rng.integers(0, P, (T, N)))
    valid = jnp.asarray(rng.random((T, N)) > 0.05)
    return ret, cap, styles, industry, valid


def test_run_fused_matches_run():
    panels = _risk_panel()
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48)
    ref = RiskModel(*panels, n_industries=4, config=cfg).run()
    out = RiskModel(*panels, n_industries=4, config=cfg).run_fused()
    for name, a, b in zip(ref._fields, ref, out):
        a, b = np.asarray(a), np.asarray(b)
        # one fused XLA program vs per-stage dispatch: same math, different
        # fusion boundaries — x64 keeps the drift at the noise floor
        np.testing.assert_allclose(b, a, rtol=1e-10, atol=1e-12,
                                   equal_nan=True, err_msg=name)


def test_run_fused_compile_cache_shared_across_instances():
    # the fused step is a module-level jit: a second instance with the same
    # shapes and config must not retrace
    from mfm_tpu.models.risk_model import _fused_risk_step

    panels = _risk_panel()
    cfg = RiskModelConfig(eigen_n_sims=4, eigen_sim_length=32)
    RiskModel(*panels, n_industries=4, config=cfg).run_fused()
    n0 = _fused_risk_step._cache_size()
    RiskModel(*_risk_panel(seed=1), n_industries=4, config=cfg).run_fused()
    assert _fused_risk_step._cache_size() == n0


def test_cpu_jacobi_parity_with_lapack():
    # the forced CPU Jacobi path (the batch-threshold escape hatch,
    # ops/eigh.py::cpu_jacobi_batch_threshold) must agree with LAPACK
    from mfm_tpu.ops.eigh import batched_eigh, batched_eigh_weighted_diag

    rng = np.random.default_rng(3)
    x = rng.standard_normal((64, 10, 10))
    A = jnp.asarray((x + x.transpose(0, 2, 1)) / 2)
    w_l, v_l = batched_eigh(A)
    w_j, v_j = batched_eigh(A, cpu_jacobi=True)
    np.testing.assert_allclose(np.asarray(w_j), np.asarray(w_l),
                               rtol=1e-10, atol=1e-10)
    # eigenvectors compare through their projectors (signs/degenerate
    # subspaces are gauge); canonical_signs makes columns comparable here
    np.testing.assert_allclose(np.asarray(v_j), np.asarray(v_l),
                               rtol=1e-8, atol=1e-8)

    d0 = jnp.asarray(rng.random((64, 10)) + 0.5)
    wd_l, h_l = batched_eigh_weighted_diag(A, d0)
    wd_j, h_j = batched_eigh_weighted_diag(A, d0, cpu_jacobi=True)
    np.testing.assert_allclose(np.asarray(wd_j), np.asarray(wd_l),
                               rtol=1e-10, atol=1e-10)
    np.testing.assert_allclose(np.asarray(h_j), np.asarray(h_l),
                               rtol=1e-8, atol=1e-8)


def test_cpu_jacobi_batch_threshold_env(monkeypatch):
    from mfm_tpu.ops import eigh as eigh_mod

    monkeypatch.delenv("MFM_EIGH_CPU_JACOBI_BATCH", raising=False)
    assert eigh_mod.cpu_jacobi_batch_threshold() is None
    monkeypatch.setenv("MFM_EIGH_CPU_JACOBI_BATCH", "4096")
    assert eigh_mod.cpu_jacobi_batch_threshold() == 4096
    monkeypatch.setenv("MFM_EIGH_CPU_JACOBI_BATCH", "0")
    assert eigh_mod.cpu_jacobi_batch_threshold() is None


def test_compiled_memory_reports_chunk_savings():
    # the observability helper must see the stream shrinking the transient:
    # chunk=1 keeps one (1, M, K, K) slab live instead of (T, M, K, K)
    from mfm_tpu.utils.obs import compiled_memory

    covs, valid, sim_covs = _cov_panel(T=64, K=8, M=16)

    def stage(chunk):
        def f(c, v, s):
            out, ok = eigen_risk_adjust_by_time(c, v, s, sim_length=100,
                                                chunk=chunk)
            return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))
        return f

    full = compiled_memory(stage(None), covs, valid, sim_covs)
    tiny = compiled_memory(stage(1), covs, valid, sim_covs)
    if not full or not tiny:
        pytest.skip("backend reports no memory_analysis")
    assert tiny["temp_bytes"] < full["temp_bytes"]
