"""Scenario engine: batched stress tests over one served covariance.

The subsystem's contracts (mfm_tpu/scenario/):

- The IDENTITY scenario is served back bitwise-equal to the baseline —
  running zero-shock scenarios costs nothing in fidelity.
- A batch of S scenarios equals S single runs BITWISE, across geometric
  bucket boundaries: the kernel is lane-independent and the padding is
  passthrough lanes, never math.
- Correlation stress past the feasible cone goes indefinite; the gated
  PSD projection repairs it (min eig >= 0 at compute dtype) and flags
  the lane + the obs counter.
- A poisoned spec (NaN shock, corr_beta past the -1 pole) is rejected
  per-scenario; healthy batchmates' bytes are untouched.
- A quarantine counterfactual is a REAL guarded re-run with flipped
  verdicts — engine output equals a manual ``update_guarded`` with the
  same ``pre_reasons`` / ``heal_mask`` operands, bitwise.
- Steady state holds the serving discipline: <= 1 compile per S-bucket
  (assert_max_compiles), same as the query engine.

Everything bitwise is assert_array_equal / tobytes — same discipline as
tests/test_quarantine.py, whose donation rules also apply (states are
copied before reuse; panels enter models as jnp.array copies).
"""

import json
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.config import QuarantinePolicy, RiskModelConfig
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.obs import instrument as _obs
from mfm_tpu.scenario import (
    PRESETS,
    ScenarioBuilder,
    ScenarioEngine,
    ScenarioManifestError,
    ScenarioSpec,
    audit_scenario_manifest,
    build_scenario_manifest,
    make_counterfactual_fn,
    make_replay_lookup,
    preset,
    read_scenario_manifest,
    scenario_manifest_path_for,
    validate_spec,
    write_scenario_manifest,
)
from mfm_tpu.serve.guard import REASON_FORCED
from mfm_tpu.utils.contracts import assert_max_compiles

K = 6


def _base_cov(seed=0, k=K, dtype=np.float32):
    """A well-conditioned PSD baseline covariance."""
    rng = np.random.default_rng(seed)
    a = rng.normal(size=(k, k))
    return ((a @ a.T + 1e-2 * np.eye(k)) * 1e-4).astype(dtype)


@pytest.fixture(scope="module")
def engine():
    return ScenarioEngine(_base_cov())


def _mixed_specs():
    """Nine healthy specs spanning every transform axis (S=9 crosses the
    8 -> 32 bucket boundary vs the S=1 singles)."""
    return [
        ScenarioSpec.identity(),
        ScenarioBuilder("shock-add").shock("f0", add=2e-3).build(),
        ScenarioBuilder("shock-mult").shock("f1", mult=2.0).build(),
        ScenarioBuilder("shock-both").shock("f2", add=1e-3, mult=0.5).build(),
        ScenarioBuilder("regime-hot").vol_regime(3.0).build(),
        ScenarioBuilder("corr-up").correlation(0.3).build(),
        ScenarioBuilder("combo").shock("f3", mult=1.5).vol_regime(1.2)
        .correlation(-0.4).build(),
        preset("crash-2015-analog"),
        preset("corr-meltup"),
    ]


# -- spec declaration ---------------------------------------------------------

def test_spec_json_round_trip_and_hash():
    spec = (ScenarioBuilder("drill")
            .shock("f1", add=1e-3, mult=2.0).shock("f0", add=-5e-4)
            .vol_regime(1.5).correlation(0.3)
            .replay("2024-01-02", "2024-02-29")
            .flip("2024-03-04").flip("2024-03-05", heal=True).build())
    back = ScenarioSpec.from_json(spec.to_json())
    assert back == spec
    assert back.spec_hash() == spec.spec_hash()
    # canonical order: dict-built and builder-built specs hash identically
    twin = ScenarioSpec(name="drill",
                        shift={"f0": -5e-4, "f1": 1e-3},
                        scale={"f1": 2.0}, vol_mult=1.5, corr_beta=0.3,
                        replay=("2024-01-02", "2024-02-29"),
                        flip_quarantine=("2024-03-04",),
                        flip_heal=("2024-03-05",))
    assert twin.spec_hash() == spec.spec_hash()
    assert set(spec.kinds) == {"vol_shock", "vol_regime", "corr_stress",
                               "replay", "counterfactual"}
    assert ScenarioSpec.identity().is_identity
    assert ScenarioSpec.identity().kinds == ("identity",)


def test_spec_from_dict_rejects_bad_wire_forms():
    with pytest.raises(ValueError, match="JSON object"):
        ScenarioSpec.from_dict(["not", "a", "dict"])
    with pytest.raises(ValueError, match="schema_version"):
        ScenarioSpec.from_dict({"schema_version": 99, "name": "x"})
    with pytest.raises(ValueError, match="missing 'name'"):
        ScenarioSpec.from_dict({"vol_mult": 2.0})


def test_validate_spec_catches_every_poison_axis():
    names = [f"f{i}" for i in range(K)]

    def problems(**kw):
        return validate_spec(ScenarioSpec(name=kw.pop("name", "s"), **kw),
                             names)

    assert problems() == []
    assert any("non-finite" in p
               for p in problems(shift=(("f0", math.nan),)))
    assert any(">= 0" in p for p in problems(scale=(("f0", -1.0),)))
    assert any("unknown factor" in p
               for p in problems(shift=(("nope", 1.0),)))
    assert any("vol_mult" in p for p in problems(vol_mult=0.0))
    assert any("vol_mult" in p for p in problems(vol_mult=math.inf))
    assert any("corr_beta" in p for p in problems(corr_beta=-1.5))
    assert any("reversed" in p
               for p in problems(replay=("2024-06-01", "2024-01-01")))
    assert any("both ways" in p
               for p in problems(flip_quarantine=("2024-01-05",),
                                 flip_heal=("2024-01-05",)))


# -- bitwise anchors ----------------------------------------------------------

def test_identity_scenario_is_bitwise_baseline(engine):
    res, = engine.run([ScenarioSpec.identity()])
    assert res.ok and not res.psd_projected
    assert res.cov.tobytes() == engine.cov.tobytes()
    np.testing.assert_array_equal(res.vol_delta(), 0)


def test_batch_equals_singles_across_bucket_boundary(engine):
    specs = _mixed_specs()
    batch = engine.run(specs)           # S=9 -> bucket 32
    for spec, got in zip(specs, batch):
        want, = engine.run([spec])      # S=1 -> bucket 8
        assert got.ok and want.ok
        assert got.cov.tobytes() == want.cov.tobytes(), spec.name
        assert got.psd_projected == want.psd_projected, spec.name
        np.testing.assert_array_equal(got.factor_vol, want.factor_vol,
                                      err_msg=spec.name)


def test_corr_stress_past_cone_is_projected_psd():
    # stressed correlations (x1.9, clipped) of this sign pattern are
    # provably indefinite: [[1,.95,.95],[.95,1,-.95],[.95,-.95,1]]
    corr = np.array([[1.0, 0.5, 0.5],
                     [0.5, 1.0, -0.5],
                     [0.5, -0.5, 1.0]])
    sigma = np.array([0.01, 0.02, 0.03])
    cov = (corr * np.outer(sigma, sigma)).astype(np.float32)
    eng = ScenarioEngine(cov)
    before = int(_obs.SCENARIO_PSD_PROJECTIONS_TOTAL.value())
    res, = eng.run([ScenarioBuilder("meltup").correlation(0.9).build()])
    assert res.ok and res.psd_projected
    assert res.min_eig_stressed < 0
    eigs = np.linalg.eigvalsh(res.cov)          # at compute dtype
    assert eigs.min() >= 0, f"projected cov not PSD: min eig {eigs.min()}"
    assert int(_obs.SCENARIO_PSD_PROJECTIONS_TOTAL.value()) == before + 1


def test_poisoned_specs_reject_without_touching_batchmates(engine):
    healthy = _mixed_specs()
    poison = [
        ScenarioBuilder("p-nan").shock("f0", add=math.nan).build(),
        ScenarioBuilder("p-corr").correlation(-1.5).build(),
        ScenarioBuilder("p-vol").vol_regime(-1.0).build(),
        ScenarioBuilder("p-factor").shock("not-a-factor", add=1e-3).build(),
    ]
    mixed = [poison[0]] + healthy[:4] + [poison[1], poison[2]] \
        + healthy[4:] + [poison[3]]
    res = {r.spec.name: r for r in engine.run(mixed)}
    for p in poison:
        r = res[p.name]
        assert r.status == "rejected" and r.problems and r.cov is None
        assert r.vol_delta() is None
    clean = engine.run(healthy)
    for want in clean:
        got = res[want.spec.name]
        assert got.ok
        assert got.cov.tobytes() == want.cov.tobytes(), want.spec.name


def test_steady_state_holds_one_compile_per_bucket(engine):
    small = _mixed_specs()[:3]          # S=3 -> bucket 8
    big = _mixed_specs()                # S=9 -> bucket 32
    engine.run(small)                   # warm both buckets
    engine.run(big)
    with assert_max_compiles(1, "steady-state scenario buckets"):
        engine.run(big)
        engine.run(small)
        # shock values change, shapes don't: still zero new lowerings
        engine.run([ScenarioBuilder("retune").shock("f5", mult=4.0).build(),
                    ScenarioBuilder("retune2").vol_regime(0.5).build()])


def test_run_refuses_malformed_batches(engine):
    with pytest.raises(ValueError, match="at least one"):
        engine.run([])
    with pytest.raises(ValueError, match="duplicate scenario names"):
        engine.run([ScenarioSpec.identity("x"), ScenarioSpec.identity("x")])
    with pytest.raises(ValueError, match="bucket"):
        engine.run(_mixed_specs(), bucket=4)
    with pytest.raises(ValueError, match="non-finite"):
        ScenarioEngine(np.full((3, 3), np.nan, np.float32))
    with pytest.raises(ValueError, match="factor names"):
        ScenarioEngine(_base_cov(), factor_names=["just-one"])


# -- replay -------------------------------------------------------------------

def test_replay_lookup_resolves_last_valid_date_in_window():
    dates = [f"2024-01-{d:02d}" for d in (2, 3, 4, 5)]
    covs = np.stack([np.eye(2) * (i + 1) for i in range(4)])
    valid = np.array([True, True, False, True])
    lookup = make_replay_lookup(dates, covs, valid=valid)
    # window covering an invalid tail date resolves to the last VALID hit
    np.testing.assert_array_equal(lookup("2024-01-02", "2024-01-04"),
                                  covs[1])
    np.testing.assert_array_equal(lookup("2024-01-01", "2024-12-31"),
                                  covs[3])
    assert lookup("2023-01-01", "2023-12-31") is None
    with pytest.raises(ValueError, match="need"):
        make_replay_lookup(dates, covs[:2])


def test_replay_scenarios_rebase_the_shock(engine):
    dates = ["2024-01-02", "2024-01-03"]
    hist = np.stack([_base_cov(7), _base_cov(8)])
    eng = ScenarioEngine(engine.cov,
                         replay_lookup=make_replay_lookup(dates, hist))
    plain, shocked, missing = eng.run([
        ScenarioBuilder("rp").replay(*dates).build(),
        ScenarioBuilder("rp-hot").replay(*dates).vol_regime(2.0).build(),
        ScenarioBuilder("rp-miss").replay("1999-01-01",
                                          "1999-12-31").build(),
    ])
    # identity transform on a replayed base: that base, bitwise
    assert plain.ok and plain.cov.tobytes() == hist[1].tobytes()
    # shocked replay == shocking an engine whose baseline IS the window
    want, = ScenarioEngine(hist[1]).run(
        [ScenarioBuilder("rp-hot").vol_regime(2.0).build()])
    assert shocked.cov.tobytes() == want.cov.tobytes()
    assert missing.status == "rejected"
    assert any("not in the engine's history" in p for p in missing.problems)
    # no history wired in: replay specs reject instead of guessing
    none, = engine.run([ScenarioBuilder("rp").replay(*dates).build()])
    assert none.status == "rejected"


# -- quarantine counterfactuals (real guarded re-runs) ------------------------

T, N, P, Q = 32, 16, 3, 2
T0 = 24
GCFG = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=T,
                       quarantine=QuarantinePolicy(enabled=True))
SLAB_DATES = [f"2024-02-{d:02d}" for d in range(1, T - T0 + 1)]


def _panels(seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(0, 0.02, (T, N)),
        rng.lognormal(10, 1, (T, N)),
        rng.normal(size=(T, N, Q)),
        rng.integers(0, P, (T, N)),
        rng.random((T, N)) > 0.05,
    )


def _model(panels, sl=slice(None)):
    # fresh JAX-owned buffers per call: update_guarded donates its inputs
    return RiskModel(*(jnp.array(np.asarray(p)[sl]) for p in panels),
                     n_industries=P, config=GCFG)


def _copy(state):
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), state)


@pytest.fixture(scope="module")
def guarded():
    """Prefix checkpoint + the plain (unflipped) slab re-run's report."""
    panels = _panels()
    _, st = _model(panels, slice(0, T0)).init_state()
    _, report, _ = _model(panels, slice(T0, T)).update_guarded(_copy(st))
    return panels, st, report


def test_counterfactual_is_a_real_rerun_with_flipped_verdicts(guarded):
    panels, st, report = guarded
    cf = make_counterfactual_fn(_model(panels, slice(T0, T)), st, SLAB_DATES)
    base = np.asarray(report.served_cov[-1])
    eng = ScenarioEngine(base, counterfactual_fn=cf)

    flip = SLAB_DATES[2]
    got, = eng.run([ScenarioBuilder("what-if").flip(flip).build()])
    assert got.ok
    # the manual world: same slab, pre_reasons forcing that one date
    pre = np.zeros(T - T0, np.uint32)
    pre[2] = REASON_FORCED
    _, rep, _ = _model(panels, slice(T0, T)).update_guarded(
        _copy(st), pre_reasons=pre, heal_mask=np.zeros(T - T0, bool))
    want = np.asarray(rep.served_cov[-1]).astype(base.dtype)
    assert got.cov.tobytes() == want.tobytes()
    assert bool(np.asarray(rep.quarantined)[2])
    # forcing a date out moves the answer vs the unflipped world
    assert got.cov.tobytes() != base.tobytes()


def test_counterfactual_heal_forces_a_poisoned_date_healthy(guarded):
    panels, st, _ = guarded
    bad = (np.array(panels[0], copy=True),) + tuple(panels[1:])
    bad[0][T0 + 1, : int(0.6 * N)] = np.nan    # poison one slab date
    slab = lambda: _model(bad, slice(T0, T))   # noqa: E731

    _, rep_q, _ = slab().update_guarded(_copy(st))
    assert bool(np.asarray(rep_q.quarantined)[1])
    base = np.asarray(rep_q.served_cov[-1])

    cf = make_counterfactual_fn(slab(), st, SLAB_DATES)
    eng = ScenarioEngine(base, counterfactual_fn=cf)
    got, = eng.run([ScenarioBuilder("heal")
                    .flip(SLAB_DATES[1], heal=True).build()])
    assert got.ok
    heal = np.zeros(T - T0, bool)
    heal[1] = True
    _, rep_h, _ = slab().update_guarded(
        _copy(st), pre_reasons=np.zeros(T - T0, np.uint32), heal_mask=heal)
    assert not bool(np.asarray(rep_h.quarantined)[1])
    want = np.asarray(rep_h.served_cov[-1]).astype(base.dtype)
    assert got.cov.tobytes() == want.tobytes()


def test_counterfactual_guard_rails(guarded):
    panels, st, report = guarded
    cf = make_counterfactual_fn(_model(panels, slice(T0, T)), st, SLAB_DATES)
    eng = ScenarioEngine(np.asarray(report.served_cov[-1]),
                         counterfactual_fn=cf)
    outside, ambiguous = eng.run([
        ScenarioBuilder("cf-outside").flip("1999-01-01").build(),
        ScenarioBuilder("cf-replay").flip(SLAB_DATES[0])
        .replay("2024-01-01", "2024-01-31").build(),
    ])
    assert outside.status == "rejected"
    assert any("outside the slab" in p for p in outside.problems)
    assert ambiguous.status == "rejected"
    assert any("compose ambiguously" in p for p in ambiguous.problems)
    # no slab context wired in: counterfactual specs reject
    bare, = ScenarioEngine(_base_cov()).run(
        [ScenarioBuilder("cf").flip("2024-02-01").build()])
    assert bare.status == "rejected"
    with pytest.raises(ValueError, match="slab dates"):
        make_counterfactual_fn(_model(panels, slice(T0, T)), st,
                               SLAB_DATES[:-1])


def test_from_risk_state_refuses_unguarded(guarded):
    panels, _, _ = guarded
    ucfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=T)
    _, st_u = RiskModel(*(jnp.array(np.asarray(p)) for p in panels),
                        n_industries=P, config=ucfg).init_state()
    with pytest.raises(ValueError, match="no served covariance"):
        ScenarioEngine.from_risk_state(st_u)


# -- manifests ----------------------------------------------------------------

def test_manifest_round_trip_and_audit(tmp_path, engine):
    results = engine.run(_mixed_specs() + [
        ScenarioBuilder("p-nan").shock("f0", add=math.nan).build()])
    man = build_scenario_manifest(
        results, engine.factor_names, stamp_json='{"cfg": 1}',
        backend="cpu", summary=_obs.scenario_summary_from_registry(),
        staleness=engine.staleness)
    path = write_scenario_manifest(str(tmp_path), man)
    assert path == scenario_manifest_path_for(str(tmp_path))
    back = read_scenario_manifest(str(tmp_path))
    assert back["n_scenarios"] == 10 and back["n_ok"] == 9
    assert back["n_rejected"] == 1 and back["n_psd_projected"] >= 1
    ok_entries = [e for e in back["scenarios"] if e["status"] == "ok"]
    assert all("top_vol_swings" in e and "total_vol_after" in e
               for e in ok_entries)
    problems, warnings = audit_scenario_manifest(path)
    assert problems == []
    assert any("p-nan" in w for w in warnings)


def test_manifest_audit_flags_tampering_and_tears(tmp_path, engine):
    results = engine.run(_mixed_specs()[:2])
    man = build_scenario_manifest(results, engine.factor_names)
    path = write_scenario_manifest(str(tmp_path), man)

    tampered = read_scenario_manifest(path)
    tampered["scenarios"][1]["spec"]["vol_mult"] = 99.0   # edited results
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(tampered, fh)
    problems, _ = audit_scenario_manifest(path)
    assert any("spec hash mismatch" in p for p in problems)

    with open(path, "w", encoding="utf-8") as fh:          # torn write
        fh.write(json.dumps(man)[: len(json.dumps(man)) // 2])
    with pytest.raises(ScenarioManifestError, match="torn"):
        read_scenario_manifest(path)

    with open(path, "w", encoding="utf-8") as fh:          # wrong artifact
        json.dump({"schema_version": 1, "kind": "checkpoint_manifest",
                   "scenarios": []}, fh)
    with pytest.raises(ScenarioManifestError, match="not a scenario"):
        read_scenario_manifest(path)
    with pytest.raises(ScenarioManifestError, match="unreadable"):
        read_scenario_manifest(str(tmp_path / "nope.json"))


def test_preset_catalog_is_admissible(engine):
    for name in PRESETS:
        assert validate_spec(preset(name), engine.factor_names) == []
    with pytest.raises(KeyError, match="unknown preset"):
        preset("dot-com-analog")
