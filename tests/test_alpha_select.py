"""Alpha selection (mfm_tpu/alpha/select.py): pairwise-valid correlation
matrix parity vs pandas, greedy cap semantics, end-to-end selection on a
batch containing a near-duplicate, and the CLI driver."""

import json

import numpy as np
import pandas as pd
import pytest


def test_series_correlation_matches_pandas_pairwise():
    from mfm_tpu.alpha.select import series_correlation_matrix

    rng = np.random.default_rng(0)
    E, T = 7, 60
    s = rng.standard_normal((E, T))
    s[rng.random((E, T)) < 0.25] = np.nan  # ragged validity per pair
    s[5, :58] = np.nan  # only 2 dates valid -> below min_periods vs most

    got = np.asarray(series_correlation_matrix(np.asarray(s, np.float32),
                                               min_periods=3))
    want = pd.DataFrame(s.T).corr(min_periods=3).to_numpy()
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_greedy_select_cap_and_order():
    from mfm_tpu.alpha.select import greedy_select

    scores = np.array([0.5, 0.4, 0.3, np.nan, 0.2])
    corr = np.eye(5)
    corr[0, 1] = corr[1, 0] = 0.9   # 1 is redundant with 0
    corr[0, 2] = corr[2, 0] = 0.1
    corr[2, 4] = corr[4, 2] = np.nan  # undefined must not block

    out = greedy_select(scores, corr, k=3, max_corr=0.7)
    assert out["indices"] == [0, 2, 4]
    assert out["rejected"] == {1: 0}
    assert out["scores"] == [0.5, 0.3, 0.2]
    assert np.isnan(out["max_corr_to_selected"][0])  # first pick: no peers
    assert out["max_corr_to_selected"][1] == pytest.approx(0.1)

    # min_score fences out weak candidates even under k
    out = greedy_select(scores, corr, k=5, max_corr=0.7, min_score=0.25)
    assert out["indices"] == [0, 2]


def test_select_alphas_drops_near_duplicate():
    from mfm_tpu.alpha.select import select_alphas

    rng = np.random.default_rng(1)
    T, N = 120, 40
    fwd = 0.02 * rng.standard_normal((T, N))
    base = fwd + 0.05 * rng.standard_normal((T, N))   # informative
    dup = base + 1e-3 * rng.standard_normal((T, N))   # its clone
    indep = 0.05 * rng.standard_normal((T, N))        # uncorrelated noise
    alphas = np.stack([base, dup, indep]).astype(np.float32)

    out = select_alphas(alphas, np.asarray(fwd, np.float32), k=2,
                        max_corr=0.7)
    # the clones' PnL corr is ~1, so exactly one of {base, dup} survives
    # (scores are near-ties — either may win) alongside the independent one
    assert len(out["indices"]) == 2 and 2 in out["indices"]
    assert len(set(out["indices"]) & {0, 1}) == 1
    [(loser, winner)] = out["rejected"].items()
    assert {loser, winner} == {0, 1}
    assert abs(out["corr"][0, 1]) > 0.95


def test_alpha_cli_select(tmp_path, capsys):
    from mfm_tpu.cli import main

    rng = np.random.default_rng(2)
    T, N = 80, 25
    dates = pd.bdate_range("2024-01-02", periods=T)
    stocks = [f"s{i:03d}" for i in range(N)]
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    long = pd.DataFrame({
        "trade_date": np.repeat(dates, N),
        "ts_code": np.tile(stocks, T),
        "close": close.ravel(),
        "ret": np.vstack([np.full((1, N), np.nan),
                          close[1:] / close[:-1] - 1]).ravel(),
    })
    panel = str(tmp_path / "panel.csv")
    long.to_csv(panel, index=False)
    exprs = str(tmp_path / "exprs.txt")
    # expr 2 is expr 1 scaled (PnL corr 1.0) -> must be rejected
    (tmp_path / "exprs.txt").write_text(
        "cs_rank(delta(close, 3))\n"
        "2.0 * cs_rank(delta(close, 3))\n"
        "-ts_mean(ret, 5)\n")
    sel_out = str(tmp_path / "selected.txt")
    main(["--platform", "cpu", "alpha", "--exprs", exprs, "--panel", panel,
          "--out", str(tmp_path / "scores.csv"),
          "--select", "2", "--select-out", sel_out])
    rep = json.loads(capsys.readouterr().out)
    assert rep["n_selected"] == 2
    assert rep["n_rejected_by_corr"] == 1
    picked = (tmp_path / "selected.txt").read_text().splitlines()
    assert len(picked) == 2
    # exactly one of the two clones survives
    clones = {"cs_rank(delta(close, 3))", "2.0 * cs_rank(delta(close, 3))"}
    assert len(clones & set(picked)) == 1
    score = pd.read_csv(tmp_path / "scores.csv", index_col=0)
    assert int(score["selected"].sum()) == 2
    assert set(score.columns) >= {"selected", "select_rank",
                                  "select_max_corr"}
    # the second pick records its realized corr to the first
    second = score[score["select_rank"] == 1]
    assert np.isfinite(second["select_max_corr"]).all()


def test_alpha_cli_select_flag_validation(tmp_path, capsys):
    from mfm_tpu.cli import main

    # --select 0 / negative must be rejected at parse time, and
    # --select-out without --select must error rather than silently no-op
    with pytest.raises(SystemExit):
        main(["alpha", "--exprs", "x", "--panel", "y", "--select", "0"])
    with pytest.raises(SystemExit):
        main(["alpha", "--exprs", "x", "--panel", "y", "--select", "-3"])
    with pytest.raises(SystemExit):
        main(["alpha", "--exprs", "x", "--panel", "y",
              "--select-out", "sel.txt"])
    capsys.readouterr()


def test_alpha_cli_values_out(tmp_path, capsys):
    import jax.numpy as jnp

    from mfm_tpu.alpha.dsl import compile_alpha
    from mfm_tpu.cli import main
    from mfm_tpu.panel import Panel

    rng = np.random.default_rng(5)
    T, N = 40, 10
    dates = pd.bdate_range("2024-01-02", periods=T)
    stocks = [f"s{i:02d}" for i in range(N)]
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    long = pd.DataFrame({
        "trade_date": np.repeat(dates, N),
        "ts_code": np.tile(stocks, T),
        "close": close.ravel(),
        "ret": np.vstack([np.full((1, N), np.nan),
                          close[1:] / close[:-1] - 1]).ravel(),
    })
    panel = str(tmp_path / "panel.csv")
    long.to_csv(panel, index=False)
    (tmp_path / "exprs.txt").write_text(
        "cs_rank(delta(close, 2))\n-ts_mean(ret, 3)\n")
    vout = str(tmp_path / "values.parquet")
    main(["--platform", "cpu", "alpha", "--exprs", str(tmp_path / "exprs.txt"),
          "--panel", panel, "--out", str(tmp_path / "scores.csv"),
          "--values-out", vout])
    rep = json.loads(capsys.readouterr().out)
    assert rep["values_out"] == vout

    got = pd.read_parquet(vout)
    assert list(got.columns) == ["trade_date", "ts_code",
                                 "alpha_0000", "alpha_0001"]
    assert len(got) == T * N
    # values round-trip: the long column equals a direct DSL evaluation
    p = Panel.from_long(long)
    direct = compile_alpha("cs_rank(delta(close, 2))")(
        {"close": jnp.asarray(p.fields["close"], jnp.float32)})
    np.testing.assert_allclose(
        got["alpha_0000"].to_numpy().reshape(T, N), np.asarray(direct),
        rtol=1e-5, equal_nan=True)
    # the column map names every exported expression
    lines = (tmp_path / "values.parquet.exprs.txt").read_text().splitlines()
    assert lines == ["alpha_0000\tcs_rank(delta(close, 2))",
                     "alpha_0001\t-ts_mean(ret, 3)"]


def test_greedy_select_invariant_random():
    """Property: on random inputs, every selected pair respects the cap and
    every rejection names a genuinely-over-cap selected blocker."""
    from mfm_tpu.alpha.select import greedy_select

    rng = np.random.default_rng(11)
    for trial in range(20):
        E = int(rng.integers(2, 25))
        scores = rng.standard_normal(E)
        scores[rng.random(E) < 0.2] = np.nan
        A = rng.standard_normal((E, E))
        corr = np.clip((A + A.T) / 2, -1, 1)
        np.fill_diagonal(corr, 1.0)
        corr[rng.random((E, E)) < 0.1] = np.nan
        corr = np.triu(corr) + np.triu(corr, 1).T  # keep symmetric with NaNs
        cap = float(rng.uniform(0.2, 0.9))
        k = int(rng.integers(1, E + 1))

        out = greedy_select(scores, corr, k=k, max_corr=cap)
        sel = out["indices"]
        assert len(sel) <= k
        for a in range(len(sel)):
            for b in range(a + 1, len(sel)):
                c = corr[sel[a], sel[b]]
                assert not (np.isfinite(c) and abs(c) > cap), (trial, sel)
        for loser, blocker in out["rejected"].items():
            assert blocker in sel
            assert abs(corr[loser, blocker]) > cap
        for i in sel:
            assert np.isfinite(scores[i])


def test_alpha_cli_min_ic_fence(tmp_path, capsys):
    from mfm_tpu.cli import main

    rng = np.random.default_rng(6)
    T, N = 60, 15
    dates = pd.bdate_range("2024-01-02", periods=T)
    stocks = [f"s{i}" for i in range(N)]
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    pd.DataFrame({
        "trade_date": np.repeat(dates, N),
        "ts_code": np.tile(stocks, T),
        "close": close.ravel(),
        "ret": np.vstack([np.full((1, N), np.nan),
                          close[1:] / close[:-1] - 1]).ravel(),
    }).to_csv(tmp_path / "panel.csv", index=False)
    (tmp_path / "e.txt").write_text("cs_rank(delta(close, 2))\n"
                                    "-ts_mean(ret, 3)\n")
    # an impossible floor selects nothing, even with k available
    main(["--platform", "cpu", "alpha", "--exprs", str(tmp_path / "e.txt"),
          "--panel", str(tmp_path / "panel.csv"),
          "--out", str(tmp_path / "s.csv"), "--select", "2",
          "--min-ic", "0.99"])
    rec = json.loads(capsys.readouterr().out)
    assert rec["n_selected"] == 0
