"""Fault-injection drills for the checkpoint write/load protocol and the
transport retry path (mfm_tpu/utils/chaos.py, data/artifacts.py fencing,
data/etl.py::with_retry).

The fast subset (no marker) runs in tier-1: byte-fault detection, fencing
refusal/heal, retry-schedule determinism — all in-process, no jax.  The
real crash drills — SIGKILL-ing a subprocess at a named protocol point —
carry ``chaos`` (and ``slow``): run them with ``pytest -m chaos``.  The
full recovery matrix, including bitwise-resume assertions over the risk
pipeline, lives in ``tools/faultinject.py``.
"""

import os
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

from mfm_tpu.data.artifacts import (
    ArtifactCorruptError,
    ArtifactStaleError,
    load_artifact,
    read_pointer,
    save_artifact,
)
from mfm_tpu.data.etl import with_retry
from mfm_tpu.utils.chaos import (
    FlakyStore,
    chaos_point,
    corrupt_file,
    flaky,
    plan_suite,
    truncate_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _save(path, gen_payload=0, fenced=True):
    save_artifact(path, {"x": np.arange(12.0) + gen_payload,
                         "y": np.eye(3)},
                  {"kind": "test", "note": gen_payload}, fenced=fenced)


# -- byte-level faults (fast, tier-1) ---------------------------------------

def test_truncation_is_detected(tmp_path):
    p = str(tmp_path / "a.npz")
    _save(p)
    truncate_file(p, 64)
    with pytest.raises(ArtifactCorruptError):
        load_artifact(p)


def test_bit_corruption_is_detected(tmp_path):
    p = str(tmp_path / "a.npz")
    _save(p)
    offsets = corrupt_file(p, 8, seed=3)
    assert len(offsets) == 8
    with pytest.raises(ArtifactCorruptError):
        load_artifact(p, fenced=True)


def test_force_never_bypasses_corruption_checks(tmp_path):
    """``force`` overrides FENCING only; a checksum mismatch is still a
    refusal — forcing a load must never hand back corrupt arrays."""
    p = str(tmp_path / "a.npz")
    _save(p)
    corrupt_file(p, 8, seed=4)
    with pytest.raises(ArtifactCorruptError):
        load_artifact(p, fenced=True, force=True)


# -- generation fencing (fast, tier-1) --------------------------------------

def test_stale_generation_refused_then_forced(tmp_path):
    p = str(tmp_path / "state.npz")
    backup = str(tmp_path / "state.gen1.bak")
    _save(p, 1)
    shutil.copy2(p, backup)
    _save(p, 2)
    _, meta = load_artifact(p, fenced=True)
    assert meta["generation"] == 2

    # yesterday's backup restored over today's file: generation 1 < pointer 2
    shutil.copy2(backup, p)
    with pytest.raises(ArtifactStaleError):
        load_artifact(p, fenced=True)
    arrays, meta = load_artifact(p, fenced=True, force=True)
    assert meta["generation"] == 1 and meta["note"] == 1
    np.testing.assert_array_equal(arrays["x"], np.arange(12.0) + 1)


def test_pointer_heals_forward(tmp_path):
    """File generation AHEAD of the pointer (a crash between rename and
    pointer swap) is the torn-write recovery case: the load accepts the
    file and advances the pointer to match."""
    import json

    p = str(tmp_path / "state.npz")
    _save(p, 1)
    _save(p, 2)
    # rewind the pointer to generation 1, as if the swap never happened
    ptr = str(tmp_path / "latest.json")
    with open(ptr) as f:
        table = json.load(f)
    table["state.npz"]["generation"] = 1
    with open(ptr, "w") as f:
        json.dump(table, f)

    _, meta = load_artifact(p, fenced=True)
    assert meta["generation"] == 2
    assert read_pointer(p)["generation"] == 2, "pointer must heal forward"


# -- retry / transport faults (fast, tier-1) --------------------------------

def test_with_retry_exponential_jitter_schedule():
    sleeps = []
    fn = flaky(lambda: "ok", n_failures=2)
    got = with_retry(fn, attempts=4, backoff_s=0.25, sleep=sleeps.append,
                     exponential=True, jitter=0.5, seed=11,
                     retryable=(ConnectionError,))
    assert got == "ok"
    assert len(sleeps) == 2
    for i, d in enumerate(sleeps):
        base = 0.25 * 2.0 ** i
        assert 0.5 * base <= d <= 1.5 * base, (i, d)
    # seeded: the same outage replays the same schedule
    sleeps2 = []
    with_retry(flaky(lambda: "ok", n_failures=2), attempts=4, backoff_s=0.25,
               sleep=sleeps2.append, exponential=True, jitter=0.5, seed=11,
               retryable=(ConnectionError,))
    assert sleeps2 == sleeps


def test_with_retry_nonretryable_raises_immediately():
    calls = []
    sleeps = []

    def fn():
        calls.append(1)
        raise TypeError("programming error — retrying cannot fix this")

    with pytest.raises(TypeError):
        with_retry(fn, attempts=5, backoff_s=0.25, sleep=sleeps.append,
                   retryable=(ConnectionError, TimeoutError))
    assert len(calls) == 1 and sleeps == []


def test_with_retry_exhaustion_reraises_last():
    fn = flaky(lambda: "ok", n_failures=99)
    sleeps = []
    with pytest.raises(ConnectionError):
        with_retry(fn, attempts=3, backoff_s=0.0, sleep=sleeps.append,
                   retryable=(ConnectionError,))
    assert len(sleeps) == 2  # no sleep after the final attempt


def test_with_retry_stamps_phase_on_exhaustion():
    """The fleet transport separates "never connected" from "lost
    mid-batch" by the ``phase`` attr stamped on the exhausted exception
    — surfaced per replica in fleet_manifest.json's failure_phases."""
    fn = flaky(lambda: "ok", n_failures=99)
    with pytest.raises(ConnectionError) as exc:
        with_retry(fn, attempts=2, backoff_s=0.0, sleep=lambda s: None,
                   retryable=(ConnectionError,), phase="connect")
    assert exc.value.phase == "connect"
    assert exc.value.attempts == 2
    # no phase requested -> no attr invented
    with pytest.raises(ConnectionError) as exc2:
        with_retry(flaky(lambda: "ok", n_failures=99), attempts=2,
                   backoff_s=0.0, sleep=lambda s: None,
                   retryable=(ConnectionError,))
    assert not hasattr(exc2.value, "phase")


def test_flaky_store_fails_then_delegates():
    class Store:
        def __init__(self):
            self.rows = 0

        def insert(self, name, df, unique=None):
            self.rows += 1
            return self.rows

        def last_date(self, name):
            return "2020-01-02"

    inner = Store()
    st = FlakyStore(inner, n_failures=2, methods=("insert",))
    assert st.last_date("t") == "2020-01-02"  # un-wrapped methods untouched
    for _ in range(2):
        with pytest.raises(ConnectionError):
            st.insert("t", None)
    assert st.insert("t", None) == 1 and inner.rows == 1


def test_plan_suite_is_deterministic():
    a, b = plan_suite(5), plan_suite(5)
    assert a == b
    names = [p.name for p in a]
    assert len(set(names)) == len(names)
    assert {p.kind for p in a} == {"truncate", "corrupt", "kill",
                                   "kill_manifest", "nan_slab",
                                   "outlier_slab", "universe_slab",
                                   "flaky_store", "query_kill",
                                   "query_poison", "query_overflow",
                                   "query_swap", "query_steady",
                                   "scenario_kill", "scenario_poison",
                                   "trace_kill", "eigen_kill",
                                   "shard_kill", "grad_kill",
                                   "fleet_kill", "fleet_kill_host",
                                   "fleet_wedge", "cache_stale",
                                   "sweep_kill",
                                   "sync_schedule_coalescer",
                                   "sync_schedule_cache",
                                   "flightrec_kill"}
    assert len({p.seed for p in a}) == len(a)


def test_chaos_point_is_inert_when_unset(monkeypatch):
    monkeypatch.delenv("MFM_CHAOS_KILL", raising=False)
    chaos_point("save_artifact.after_tmp", "/any/path")  # must not kill us
    monkeypatch.setenv("MFM_CHAOS_KILL", "save_artifact.after_tmp")
    monkeypatch.setenv("MFM_CHAOS_KILL_MATCH", "no-such-substring")
    chaos_point("save_artifact.after_tmp", "/any/path")  # match gate holds


# -- real crash drills (subprocess SIGKILL; pytest -m chaos) ----------------

_SAVE_SCRIPT = """\
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from mfm_tpu.data.artifacts import save_artifact
save_artifact({path!r}, {{"x": np.arange(12.0) + {stamp}}},
              {{"kind": "test", "note": {stamp}}}, fenced=True)
"""


def _save_in_subprocess(path, stamp, kill_at=None):
    env = dict(os.environ)
    env.pop("MFM_CHAOS_KILL", None)
    env.pop("MFM_CHAOS_KILL_MATCH", None)
    if kill_at:
        env["MFM_CHAOS_KILL"] = kill_at
    return subprocess.run(
        [sys.executable, "-c",
         _SAVE_SCRIPT.format(repo=REPO, path=path, stamp=stamp)],
        env=env, capture_output=True, text=True, timeout=120)


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_after_tmp_preserves_old_checkpoint(tmp_path):
    p = str(tmp_path / "state.npz")
    assert _save_in_subprocess(p, 1).returncode == 0

    proc = _save_in_subprocess(p, 2, kill_at="save_artifact.after_tmp")
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    arrays, meta = load_artifact(p, fenced=True)
    assert meta["note"] == 1 and meta["generation"] == 1
    np.testing.assert_array_equal(arrays["x"], np.arange(12.0) + 1)
    # the retried write wins cleanly over the torn tmp
    assert _save_in_subprocess(p, 2).returncode == 0
    _, meta = load_artifact(p, fenced=True)
    assert meta["note"] == 2 and meta["generation"] == 2


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_after_rename_heals_pointer(tmp_path):
    p = str(tmp_path / "state.npz")
    assert _save_in_subprocess(p, 1).returncode == 0

    proc = _save_in_subprocess(p, 2, kill_at="save_artifact.after_rename")
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # new file live, pointer still at generation 1 — load accepts and heals
    assert read_pointer(p)["generation"] == 1
    arrays, meta = load_artifact(p, fenced=True)
    assert meta["note"] == 2 and meta["generation"] == 2
    assert read_pointer(p)["generation"] == 2
