"""SLO burn-rate engine (mfm_tpu/obs/slo.py): spec validation, the
two-window burn discipline over a fake clock with injected registry
readings, the fast/slow state ranking, sample pruning, and the process
engine slot ``/healthz`` + the manifests read through.

Every scenario drives a :class:`SloEngine` subclass whose registry
reader is a mutable feed — the burn math is deterministic arithmetic
over cumulative counters, so no sleeping and no live traffic."""

import pytest

from mfm_tpu.obs.slo import (
    DEFAULT_SLOS,
    FAST_BURN_THRESHOLD,
    SLOW_BURN_THRESHOLD,
    SloEngine,
    SloSpec,
    install,
    installed_summary,
    reset_slo,
)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _FedEngine(SloEngine):
    """SloEngine reading an injected feed instead of the live registry."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.feed = {"total": 0, "ok": 0,
                     "lat_cum": [0, 0, 0],
                     "lat_bounds": [0.1, 0.5, float("inf")],
                     "staleness": 0.0}

    def _read_registry(self):
        return {k: (list(v) if isinstance(v, list) else v)
                for k, v in self.feed.items()}

    def traffic(self, n, *, n_ok=None, n_fast=None):
        """Add ``n`` requests: ``n_ok`` answered ok (default all),
        ``n_fast`` within the 0.5 s latency objective (default all)."""
        n_ok = n if n_ok is None else n_ok
        n_fast = n if n_fast is None else n_fast
        f = self.feed
        f["total"] += n
        f["ok"] += n_ok
        f["lat_cum"] = [f["lat_cum"][0] + n_fast,
                        f["lat_cum"][1] + n_fast,
                        f["lat_cum"][2] + n]


def _by_name(summary):
    return {s["name"]: s for s in summary["slos"]}


def _engine():
    clk = _Clock()
    return _FedEngine(clock=clk), clk


# -- spec validation ----------------------------------------------------------

def test_spec_validation_rejects_bad_kind_and_objective():
    with pytest.raises(ValueError, match="unknown SLO kind"):
        SloSpec("x", "latency_p50", 0.5)
    with pytest.raises(ValueError, match="availability objective"):
        SloSpec("x", "availability", 1.0)
    with pytest.raises(ValueError, match="must be >= 0"):
        SloSpec("x", "p99_latency", -1.0)


def test_budget_is_complement_for_availability_tail_for_the_rest():
    assert SloSpec("a", "availability", 0.99).budget() == pytest.approx(0.01)
    assert SloSpec("l", "p99_latency", 0.5).budget() == pytest.approx(0.01)
    assert SloSpec("s", "staleness", 5.0).budget() == pytest.approx(0.01)


def test_engine_rejects_empty_specs_and_inverted_windows():
    with pytest.raises(ValueError, match="at least one"):
        SloEngine(())
    with pytest.raises(ValueError, match="fast <= slow"):
        SloEngine(fast_window_s=7200.0, slow_window_s=3600.0)


# -- burn states --------------------------------------------------------------

def test_no_traffic_is_ok_everywhere():
    eng, _clk = _engine()
    out = eng.evaluate()
    assert out["worst_state"] == "ok"
    assert all(s["state"] == "ok" and s["burn_fast"] == 0.0
               for s in out["slos"])
    assert out["fast_burn_threshold"] == FAST_BURN_THRESHOLD
    assert out["slow_burn_threshold"] == SLOW_BURN_THRESHOLD


def test_clean_traffic_burns_nothing():
    eng, clk = _engine()
    eng.evaluate()
    clk.t = 60.0
    eng.traffic(100)
    out = eng.evaluate()
    assert out["worst_state"] == "ok"
    assert _by_name(out)["availability"]["burn_fast"] == 0.0


def test_error_storm_is_a_fast_burn_page():
    eng, clk = _engine()
    eng.evaluate()                         # baseline at t=0
    clk.t = 60.0
    eng.traffic(100, n_ok=50)              # 50% errors vs a 1% budget
    out = eng.evaluate()
    avail = _by_name(out)["availability"]
    assert avail["burn_fast"] == pytest.approx(50.0)
    assert avail["state"] == "fast_burn"
    assert out["worst_state"] == "fast_burn"


def test_old_errors_decay_to_slow_burn_ticket():
    """10% errors an hour's-width ago, clean since: the fast window has
    recovered (no page) but the slow window still burns >= 3x (ticket)."""
    eng, clk = _engine()
    eng.evaluate()                         # t=0 baseline
    clk.t = 100.0
    eng.traffic(100, n_ok=90)              # the bad stretch
    eng.sample()
    clk.t = 450.0                          # fast window (300 s) has rolled
    eng.traffic(100)                       # clean recovery traffic
    out = eng.evaluate()
    avail = _by_name(out)["availability"]
    assert avail["burn_fast"] == 0.0
    assert avail["burn_slow"] == pytest.approx(5.0)
    assert avail["state"] == "slow_burn"
    assert out["worst_state"] == "slow_burn"


def test_latency_tail_burn_reads_the_cumulative_buckets():
    eng, clk = _engine()
    eng.evaluate()
    clk.t = 60.0
    eng.traffic(100, n_fast=80)            # 20% over the 500 ms objective
    out = eng.evaluate()
    lat = _by_name(out)["p99-latency"]
    assert lat["burn_fast"] == pytest.approx(20.0)
    assert lat["state"] == "fast_burn"


def test_staleness_burns_bad_time_fraction():
    eng, clk = _engine()
    eng.evaluate()                         # sample 0: fresh
    clk.t = 60.0
    eng.feed["staleness"] = 10.0           # over the 5-date objective
    out = eng.evaluate()                   # sample 1: stale
    stale = _by_name(out)["staleness"]
    # 1 of 2 window samples over the objective -> 50% bad time / 1% budget
    assert stale["burn_fast"] == pytest.approx(50.0)
    assert stale["state"] == "fast_burn"


def test_sample_pruning_keeps_one_full_width_baseline():
    eng, clk = _engine()
    for i in range(10):
        clk.t = i * 1000.0
        eng.sample()
    # slow window is 3600 s: everything older than one window is pruned
    # EXCEPT one sample, so a full-width baseline always exists
    with eng._lock:
        ts = [t for t, _ in eng._samples]
    assert ts[0] <= clk.t - eng.slow_window_s
    assert all(clk.t - t < eng.slow_window_s for t in ts[1:])


# -- the process engine slot --------------------------------------------------

def test_install_slot_feeds_summary_and_disarms():
    try:
        install(SloEngine())
        out = installed_summary()
        assert out is not None and out["schema"] == 1
        assert {s["name"] for s in out["slos"]} == \
            {s.name for s in DEFAULT_SLOS}
    finally:
        reset_slo()
    assert installed_summary() is None


def test_states_mirror_onto_the_registry_gauges():
    from mfm_tpu.obs.instrument import SLO_BURN_RATE, SLO_STATE
    eng, clk = _engine()
    eng.evaluate()
    clk.t = 60.0
    eng.traffic(100, n_ok=50)
    eng.evaluate()
    burn = {k: v for k, v in SLO_BURN_RATE.series().items()}
    assert burn[("availability", "fast")] == pytest.approx(50.0)
    states = {k[0]: v for k, v in SLO_STATE.series().items()}
    assert states["availability"] == 2.0   # fast_burn ranks 2
