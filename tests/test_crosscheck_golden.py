"""The independent end-to-end crosscheck tool (tools/crosscheck_golden.py)
must pass its own gates hermetically: framework factor table vs the
pandas-only golden pipeline, both computed from the same raw synthetic
store (the committed CROSSCHECK.json is the full-windows run of this)."""

import importlib.util
import json
import os
import sys


def test_quick_profile_passes_gates(tmp_path, capsys):
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "crosscheck_golden.py")
    spec = importlib.util.spec_from_file_location("crosscheck_golden", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "crosscheck.json")
    rc = mod.main(["--profile", "quick", "--out", out])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["verdict"]["parity"] is True and doc["failed"] == []
    styles = {dst for _, dst in mod.BARRA_OUTPUT_STYLES}
    assert styles <= set(doc["per_factor"])
    for fac, r in doc["per_factor"].items():
        assert r["n_overlap"] > 0, fac
        assert r["pearson"] >= 0.9999, (fac, r)
