"""The telemetry subsystem (mfm_tpu/obs/): metrics registry semantics,
Prometheus/JSONL exporters, run manifests, and model-health monitors.

The exporter tests pin the two wire formats the outside world consumes:
the Prometheus textfile round-trips through our own strict parser (names,
labels, types, histogram bucket folding), and the JSONL event stream keeps
its required-key schema stable.  The manifest tests include the crash
drill: SIGKILL between the tmp write and the rename must never leave a
torn ``run_manifest.json`` (same ``MFM_CHAOS_KILL`` mechanism as
tests/test_chaos.py — the subprocess drill carries ``chaos``/``slow``; the
torn-file *detection* paths run in tier-1).
"""

import json
import math
import os
import signal
import subprocess
import sys
import types

import numpy as np
import pytest

from mfm_tpu.obs.exporters import (
    EVENT_REQUIRED_KEYS,
    EventLog,
    parse_prometheus,
    render_prometheus,
    write_prometheus_textfile,
)
from mfm_tpu.obs.health import HealthThresholds, evaluate_health
from mfm_tpu.obs.manifest import (
    ManifestError,
    build_run_manifest,
    manifest_path_for,
    read_run_manifest,
    write_run_manifest,
)
from mfm_tpu.obs.metrics import MetricsRegistry, snapshot_json

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _registry_with_traffic():
    reg = MetricsRegistry()
    c = reg.counter("mfm_test_total", "a counter", labelnames=("kind",))
    c.inc(3, kind="good")
    c.inc(kind="bad")
    g = reg.gauge("mfm_test_gauge", "a gauge")
    g.set_value(2.5)
    h = reg.histogram("mfm_test_seconds", "a histogram",
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    return reg


# -- registry semantics -------------------------------------------------------

def test_counter_is_monotonic_and_rejects_negative():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "c")
    c.inc()
    c.inc(2.0)
    assert c.value() == 3.0
    with pytest.raises(ValueError):
        c.inc(-1.0)


def test_declare_once_conflicting_redeclaration_raises():
    reg = MetricsRegistry()
    reg.counter("x_total", "x", labelnames=("a",))
    # same declaration -> same object (idempotent)
    assert reg.counter("x_total", "x", labelnames=("a",)) is \
        reg.counter("x_total", "x", labelnames=("a",))
    with pytest.raises(ValueError):
        reg.counter("x_total", "x", labelnames=("b",))   # labels differ
    with pytest.raises(ValueError):
        reg.gauge("x_total", "x")                        # type differs


def test_histogram_cumulative_buckets_are_monotone_and_quantiles_bracket():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "h", buckets=(0.01, 0.1, 1.0, 10.0))
    rng = np.random.default_rng(0)
    vals = rng.uniform(0.02, 5.0, size=500)
    for v in vals:
        h.observe(float(v))
    cum = h.cumulative()
    counts = [c for _, c in cum]
    assert counts == sorted(counts), "cumulative bucket counts must be " \
                                     "monotone non-decreasing"
    assert cum[-1][0] == math.inf and cum[-1][1] == len(vals)
    # bucket-interpolated quantiles can only promise bucket-level accuracy:
    # the estimate must land within the bucket containing the true quantile
    for q in (0.1, 0.5, 0.9):
        est = h.quantile_est(q)
        true = float(np.quantile(vals, q))
        bounds = (0.0, 0.01, 0.1, 1.0, 10.0)
        lo = max(b for b in bounds if b <= true)
        hi = min(b for b in bounds if b > true)
        assert lo <= est <= hi, (q, est, true)
    assert math.isnan(reg.histogram("empty_seconds", "e").quantile_est(0.5))


# -- Prometheus exporter ------------------------------------------------------

def test_prometheus_render_parse_round_trip():
    reg = _registry_with_traffic()
    families = parse_prometheus(render_prometheus(reg))
    assert families["mfm_test_total"]["type"] == "counter"
    assert families["mfm_test_gauge"]["type"] == "gauge"
    assert families["mfm_test_seconds"]["type"] == "histogram"
    by_labels = {tuple(sorted(lbl.items())): v for _, lbl, v
                 in families["mfm_test_total"]["samples"]}
    assert by_labels[(("kind", "good"),)] == 3.0
    assert by_labels[(("kind", "bad"),)] == 1.0
    gauge = families["mfm_test_gauge"]["samples"]
    assert len(gauge) == 1 and gauge[0][2] == 2.5
    hist = families["mfm_test_seconds"]["samples"]
    buckets = {lbl["le"]: v for name, lbl, v in hist
               if name.endswith("_bucket")}
    assert buckets["0.1"] == 1.0 and buckets["1.0"] == 3.0
    assert buckets["+Inf"] == 4.0
    count = [v for name, _, v in hist if name.endswith("_count")]
    total = [v for name, _, v in hist if name.endswith("_sum")]
    assert count == [4.0] and abs(total[0] - 6.05) < 1e-9


def test_prometheus_textfile_is_parse_validated_and_atomic(tmp_path):
    reg = _registry_with_traffic()
    path = str(tmp_path / "metrics.prom")
    text = write_prometheus_textfile(path, reg)
    assert open(path).read() == text
    assert "mfm_test_total" in parse_prometheus(open(path).read())
    assert not [f for f in os.listdir(tmp_path) if f != "metrics.prom"], \
        "no tmp litter after the atomic rename"


def test_prometheus_parser_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE x sometype\nx 1\n")
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE x counter\nx not-a-number\n")


# -- JSONL event stream -------------------------------------------------------

def test_event_log_schema_and_level_gate(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, min_level="info")
    log.emit("debug", "ignored_event")
    log.emit("info", "guarded_update", dates=4, quarantined=1)
    log.emit("error", "checkpoint_corrupt", path="x.npz")
    lines = [json.loads(ln) for ln in open(path)]
    assert [e["event"] for e in lines] == ["guarded_update",
                                           "checkpoint_corrupt"]
    for e in lines:
        for k in EVENT_REQUIRED_KEYS:
            assert k in e, f"event lost required key {k!r}"
    assert lines[0]["dates"] == 4 and lines[0]["quarantined"] == 1
    log.set_level("error")
    log.emit("info", "now_ignored")
    assert len(open(path).read().splitlines()) == 2


def test_snapshot_json_is_schema_versioned_and_stable():
    reg = _registry_with_traffic()
    snap = json.loads(snapshot_json(reg))
    assert snap["schema"] == 1
    m = snap["metrics"]["mfm_test_seconds"]
    assert m["type"] == "histogram"
    # re-serializing must be byte-identical modulo the timestamp
    a, b = (json.loads(snapshot_json(reg)) for _ in range(2))
    a.pop("taken_at_unix"), b.pop("taken_at_unix")
    assert a == b


# -- run manifest -------------------------------------------------------------

def _write_valid_manifest(dirpath, health=None):
    man = build_run_manifest(stamp_json={"__tuple__": ["x", 1]},
                             checkpoint=os.path.join(dirpath, "state.npz"),
                             backend="cpu", health=health)
    return write_run_manifest(dirpath, man)


def test_manifest_round_trip_and_path_convention(tmp_path):
    d = str(tmp_path)
    _write_valid_manifest(d)
    p = manifest_path_for(os.path.join(d, "state.npz"))
    assert os.path.basename(p) == "run_manifest.json"
    man = read_run_manifest(p)
    assert man["schema_version"] == 1
    assert man["checkpoint"] == "state.npz"
    assert man["health"]["status"] == "unknown"


def test_manifest_reader_rejects_torn_and_invalid(tmp_path):
    p = str(tmp_path / "run_manifest.json")
    open(p, "w").write('{"schema_version": 1, "health": {"status"')  # torn
    with pytest.raises(ManifestError):
        read_run_manifest(p)
    open(p, "w").write(json.dumps({"schema_version": 999,
                                   "health": {"status": "ok"}}))
    with pytest.raises(ManifestError):
        read_run_manifest(p)
    open(p, "w").write(json.dumps({"schema_version": 1}))  # no health
    with pytest.raises(ManifestError):
        read_run_manifest(p)


_MANIFEST_SCRIPT = """\
import sys
sys.path.insert(0, {repo!r})
from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest
write_run_manifest({dir!r}, build_run_manifest(
    checkpoint="state.npz", backend="cpu",
    extra={{"stamp": {stamp}}}))
"""


def _manifest_in_subprocess(dirpath, stamp, kill=False):
    env = dict(os.environ)
    env.pop("MFM_CHAOS_KILL", None)
    if kill:
        env["MFM_CHAOS_KILL"] = "run_manifest.after_tmp"
    return subprocess.run(
        [sys.executable, "-c",
         _MANIFEST_SCRIPT.format(repo=REPO, dir=dirpath, stamp=stamp)],
        env=env, capture_output=True, text=True, timeout=120)


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_at_manifest_write_leaves_no_torn_manifest(tmp_path):
    d = str(tmp_path)
    assert _manifest_in_subprocess(d, 1).returncode == 0
    before = read_run_manifest(os.path.join(d, "run_manifest.json"))
    assert before["stamp"] == 1

    proc = _manifest_in_subprocess(d, 2, kill=True)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # the crash fell between tmp write and rename: the OLD manifest is
    # still the live one, bitwise valid
    after = read_run_manifest(os.path.join(d, "run_manifest.json"))
    assert after == before
    # and the retried write wins cleanly
    assert _manifest_in_subprocess(d, 2).returncode == 0
    assert read_run_manifest(
        os.path.join(d, "run_manifest.json"))["stamp"] == 2


# -- model-health monitors ----------------------------------------------------

def _healthy_outputs(T=240, K=4, seed=0):
    rng = np.random.default_rng(seed)
    fr = 0.01 * rng.standard_normal((T, K))
    cov = np.einsum("ti,tj->tij", fr, fr) + np.eye(K) * 1e-4
    return types.SimpleNamespace(
        factor_ret=fr, r2=0.3 + 0.05 * rng.random(T),
        eigen_cov=cov, eigen_valid=np.ones(T, bool))


def test_health_short_history_is_unknown_not_degraded():
    out = _healthy_outputs(T=10)
    out.factor_ret[:] = np.nan    # nothing measurable anywhere
    reg = MetricsRegistry()
    verdict = evaluate_health(out, registry=reg)
    assert verdict["status"] == "unknown"
    assert all(rec["value"] is None and rec["ok"]
               for rec in verdict["checks"].values())
    assert reg.gauge("mfm_model_health", "").value() == -1.0


def test_health_r2_collapse_and_outliers_degrade():
    out = _healthy_outputs()
    out.r2[-60:] = 0.05                       # explanatory power collapsed
    out.factor_ret[-5:, 0] = 0.8              # absurd factor returns
    reg = MetricsRegistry()
    verdict = evaluate_health(out, registry=reg)
    assert verdict["status"] == "degraded"
    assert not verdict["checks"]["r2_drop"]["ok"]
    assert not verdict["checks"]["factor_ret_outlier_frac"]["ok"]
    assert reg.gauge("mfm_model_health", "").value() == 0.0


def test_health_quarantine_rate_check_uses_guard_summary():
    out = _healthy_outputs(T=10)              # monitors all skip...
    verdict = evaluate_health(out, registry=MetricsRegistry(),
                              guard_summary={"served_dates": 50,
                                             "quarantined_dates": 10,
                                             "quarantine_rate": 0.2})
    # ...but the quarantine rate alone is measured, and damning
    assert verdict["status"] == "degraded"
    assert not verdict["checks"]["quarantine_rate"]["ok"]
    ok = evaluate_health(out, registry=MetricsRegistry(),
                         guard_summary={"served_dates": 50,
                                        "quarantined_dates": 0,
                                        "quarantine_rate": 0.0})
    assert ok["status"] == "ok"


def test_health_thresholds_are_tunable():
    out = _healthy_outputs()
    out.r2[-60:] = 0.05
    lax = HealthThresholds(r2_max_drop=1.0, factor_ret_outlier_z=1e9)
    verdict = evaluate_health(out, thresholds=lax,
                              registry=MetricsRegistry())
    assert verdict["checks"]["r2_drop"]["ok"]


# -- metrics CLI --------------------------------------------------------------

def test_metrics_cli_dump_snapshot_diff(tmp_path, capsys):
    from mfm_tpu.cli import main as cli_main

    reg_a, reg_b = _registry_with_traffic(), _registry_with_traffic()
    reg_b.counter("mfm_test_total", "a counter",
                  labelnames=("kind",)).inc(5, kind="good")
    a, b = tmp_path / "a", tmp_path / "b"
    for d, reg in ((a, reg_a), (b, reg_b)):
        d.mkdir()
        write_prometheus_textfile(str(d / "metrics.prom"), reg)
        (d / "metrics.json").write_text(snapshot_json(reg))

    cli_main(["metrics", "dump", str(a)])
    assert "mfm_test_total" in capsys.readouterr().out

    cli_main(["metrics", "snapshot", str(a)])
    assert json.loads(capsys.readouterr().out)["schema"] == 1

    cli_main(["metrics", "diff", str(a), str(b)])
    diff = json.loads(capsys.readouterr().out)
    key = "mfm_test_total{kind=good}"
    assert diff["series"][key]["delta"] == 5.0
    assert all(rec["delta"] != 0 for rec in diff["series"].values())

    with pytest.raises(SystemExit):
        cli_main(["metrics", "dump", str(tmp_path / "missing")])
