"""Shared storage contract test (VERDICT r3 missing #3): the same
insert/read/replace_where/replace/last_date/distinct_count semantics must
hold for every PanelStore backend.  Runs against the parquet store
unconditionally, and against :class:`mfm_tpu.data.mongo_store.MongoPanelStore`
ALWAYS: on a real localhost server when pymongo + a server exist, else on
``tests/mongofake.py`` (an in-memory pymongo implementing exactly the
surface the adapter touches) — the adapter's real logic executes in this
image either way (round-4 VERDICT missing #2).

Reference semantics under test: unique index + ``insert_many(ordered=False)``
duplicate tolerance (``update_mongo_db.py:118-128``), delete-then-insert
refresh (``:514-521``), last-date watermark (``:19-30``), distinct counts
(``verify_data.py:8``).
"""

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.data.etl import PanelStore

from tests import mongofake


def _patch_in_fake(monkeypatch):
    from mfm_tpu.data import mongo_store

    monkeypatch.setattr(mongo_store, "pymongo", mongofake)
    monkeypatch.setattr(mongo_store, "BulkWriteError",
                        mongofake.BulkWriteError)
    return mongo_store.MongoPanelStore(mongofake.FakeDatabase())


def _mongo_store(monkeypatch):
    try:
        import pymongo
    except ImportError:
        return _patch_in_fake(monkeypatch)
    from mfm_tpu.data.mongo_store import MongoPanelStore

    client = pymongo.MongoClient("localhost", 27017,
                                 serverSelectionTimeoutMS=500)
    try:
        client.admin.command("ping")
    except Exception:
        return _patch_in_fake(monkeypatch)
    db = client["mfm_tpu_contract_test"]
    client.drop_database(db.name)
    return MongoPanelStore(db)


@pytest.fixture(params=["parquet", "mongo"])
def store(request, tmp_path, monkeypatch):
    if request.param == "parquet":
        return PanelStore(str(tmp_path))
    return _mongo_store(monkeypatch)


def _frame(day, n=3, start=0):
    return pd.DataFrame({
        "ts_code": [f"{600000 + start + i}.SH" for i in range(n)],
        "trade_date": f"2024010{day}",
        "close": np.linspace(1.0, 2.0, n) + day,
    })


def test_insert_read_roundtrip(store):
    n = store.insert("px", _frame(1), unique=("ts_code", "trade_date"))
    assert n == 3
    got = store.read("px").sort_values("ts_code").reset_index(drop=True)
    assert list(got.columns) == ["ts_code", "trade_date", "close"]
    assert len(got) == 3
    # column projection
    only = store.read("px", columns=["ts_code"])
    assert list(only.columns) == ["ts_code"]


def test_duplicate_tolerant_insert(store):
    u = ("ts_code", "trade_date")
    assert store.insert("px", _frame(1), unique=u) == 3
    # full duplicate batch -> zero inserted
    assert store.insert("px", _frame(1), unique=u) == 0
    # mixed batch -> only the fresh rows land
    mixed = pd.concat([_frame(1), _frame(2)], ignore_index=True)
    assert store.insert("px", mixed, unique=u) == 3
    assert len(store.read("px")) == 6


def test_replace_where_refresh(store):
    store.insert("comp", pd.DataFrame({
        "index_code": ["A", "A", "B"],
        "trade_date": ["20240101"] * 3,
        "con_code": ["x", "y", "z"],
    }))
    store.replace_where(
        "comp",
        lambda c: (c["index_code"] == "A") & (c["trade_date"] == "20240101"),
        pd.DataFrame({"index_code": ["A"], "trade_date": ["20240101"],
                      "con_code": ["w"]}),
    )
    got = store.read("comp")
    assert sorted(got["con_code"]) == ["w", "z"]


def test_replace_full_refresh(store):
    """replace(): contents become exactly df (drop + insert_many,
    update_mongo_db.py:32-57) — including creating a fresh collection and
    shrinking an existing one."""
    store.replace("info", _frame(1, n=4))      # create
    assert len(store.read("info")) == 4
    store.replace("info", _frame(2, n=2))      # full refresh, smaller
    got = store.read("info")
    assert len(got) == 2
    assert set(got["trade_date"]) == {"20240102"}
    store.replace("info", None)                # None wipes, both backends
    assert len(store.read("info")) == 0


def test_last_date_watermark(store):
    assert store.last_date("px") is None
    store.insert("px", _frame(1), unique=("ts_code", "trade_date"))
    store.insert("px", _frame(3), unique=("ts_code", "trade_date"))
    assert store.last_date("px") == "20240103"
    # a collection without the date column is a clean None
    store.insert("info", pd.DataFrame({"ts_code": ["600000.SH"]}))
    assert store.last_date("info") is None


def test_distinct_count(store):
    store.insert("px", _frame(1, n=4), unique=("ts_code", "trade_date"))
    store.insert("px", _frame(2, n=4), unique=("ts_code", "trade_date"))
    assert store.distinct_count("px", "ts_code") == 4
    assert store.distinct_count("px", "trade_date") == 2
    assert store.distinct_count("nothing", "ts_code") == 0


def test_mongo_null_key_rows_collide(monkeypatch):
    """Mongo's non-sparse unique index treats a MISSING key column as null:
    two rows both lacking it collide, and the adapter must admit exactly
    one (dedup admission through BulkWriteError code 11000)."""
    st = _patch_in_fake(monkeypatch)
    u = ("ts_code", "trade_date")
    full = pd.DataFrame({"ts_code": ["600000.SH"],
                         "trade_date": ["20240101"], "close": [1.0]})
    assert st.insert("px", full, unique=u) == 1
    nokey = pd.DataFrame({"ts_code": ["600001.SH", "600001.SH"],
                          "trade_date": [None, None],
                          "close": [2.0, 3.0]})
    # first null-keyed row admitted, second collides with it
    assert st.insert("px", nokey, unique=u) == 1
    assert len(st.read("px")) == 2


def test_mongo_last_date_index_fallback(monkeypatch):
    """last_date's best-effort index (mongo_store.py:146-161): an
    authorization failure is cached as don't-retry (reads still answer,
    unindexed); a TRANSIENT error is NOT cached — the next call retries
    and builds the index."""
    st = _patch_in_fake(monkeypatch)
    st.insert("px", _frame(1), unique=("ts_code", "trade_date"))
    coll = st.db["px"]

    # authorization failure: answer survives, key cached as don't-retry
    coll.fail_create_index = mongofake.OperationFailure("not authorized")
    assert st.last_date("px") == "20240101"
    assert ("px", ("__date__", "trade_date")) in st._indexed
    coll.fail_create_index = None
    st.insert("px", _frame(2), unique=("ts_code", "trade_date"))
    assert st.last_date("px") == "20240102"
    assert ("trade_date",) not in coll.plain_indexes  # cached: no retry

    # transient failure on a fresh store: not cached, retried, then built
    st2 = _patch_in_fake(monkeypatch)
    st2.insert("px", _frame(1), unique=("ts_code", "trade_date"))
    coll2 = st2.db["px"]
    coll2.fail_create_index = ConnectionError("primary stepdown")
    assert st2.last_date("px") == "20240101"
    assert ("px", ("__date__", "trade_date")) not in st2._indexed
    coll2.fail_create_index = None
    assert st2.last_date("px") == "20240101"
    assert ("trade_date",) in coll2.plain_indexes  # retried and built


def test_updater_runs_on_any_backend(store):
    """The IncrementalUpdater logic is backend-agnostic: watermark resume
    works through the shared interface."""
    from mfm_tpu.data.etl import IncrementalUpdater

    class Src:
        def __init__(self):
            self.calls = []

        def fetch_daily_prices(self, trade_date):
            self.calls.append(trade_date)
            return _frame(int(trade_date[-1]))

    src = Src()
    up = IncrementalUpdater(store=store, source=src, sleep=lambda s: None)
    cal = ["20240101", "20240102", "20240103"]
    assert up.update_daily_prices(cal) == 9
    # resume: everything at/before the watermark is skipped
    src.calls.clear()
    assert up.update_daily_prices(cal + ["20240104"]) == 3
    assert src.calls == ["20240104"]
