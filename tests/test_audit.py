"""The IR-level static audit (mfm_tpu/analysis/), gated into tier-1.

Four layers, mirroring tests/test_mfmlint.py:
 1. the real tree audits strict-clean against the committed budget file,
    inside the 120 s device-free budget — which is what makes every pass
    a pre-merge regression gate;
 2. pure-function fixtures pin each pass's semantics, including the two
    historical incident reconstructions the audit exists for: PR 4's
    donation/aliasing disagreement (both directions, plus an injected
    non-donated alias in a synthetic executable header) and PR 1's s64
    retrace trap (an i64 index rung on a declared bucket ladder);
 3. the registry-completeness contract: every jit root mfmlint's call
    graph finds in the package is either a registered entrypoint or a
    justified NON_ENTRYPOINT_JITS entry — a new jit cannot dodge the
    audit silently;
 4. the committed AUDIT_r*.json snapshot verifies (seal digest, schema,
    strict-cleanliness, staleness vs the live registry/budgets), and
    ``mfm-tpu doctor --audit`` exits non-zero on a torn or tampered one.
"""

import functools
import json

import pytest

import jax
import jax.numpy as jnp

from mfm_tpu.analysis import aliasing, budgets, collectives, ir, surface
from mfm_tpu.analysis.registry import (
    NON_ENTRYPOINT_JITS,
    Cell,
    Finding,
    registry,
    registry_by_name,
)
from mfm_tpu.analysis.run import (
    latest_snapshot_path,
    main as audit_main,
    report_digest,
    run_audit,
    verify_snapshot,
)


def _codes(findings):
    return sorted(f.code for f in findings)


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# -- layer 2: A1, the donation-aliasing proof ---------------------------------

def test_parse_input_output_alias_nested_braces():
    # nested braces ({output}: (param, {param_index}, kind)) — the exact
    # shape a non-greedy regex would truncate at the first '}'
    header = ("HloModule jit_step, entry_computation_layout={...}, "
              "input_output_alias={ {1}: (0, {}, may-alias), "
              "{2, 0}: (13, {0}, must-alias) }, "
              "frontend_attributes={fingerprint=\"x\"}")
    entries = aliasing.parse_input_output_alias(header)
    assert entries == [
        {"output": "1", "param": 0, "kind": "may-alias"},
        {"output": "2,0", "param": 13, "kind": "must-alias"},
    ]
    assert aliasing.parse_input_output_alias("HloModule no_alias") == []


def test_a1_contract_mismatch_fires_both_directions():
    # contract donates what the jit doesn't: the host drops a live buffer
    f = aliasing.check_aliasing("ep", "c", {0, 1}, [True, False], [])
    assert "donation-contract-mismatch" in _codes(f)
    assert any("contract donates" in x.message for x in f)
    # jit donates what the contract retains: the PR 4 corruption class
    f = aliasing.check_aliasing("ep", "c", set(), [True], [])
    assert "donation-contract-mismatch" in _codes(f)
    assert any("PR 4" in x.message for x in f)
    # agreement is clean (modulo the info-grade inert-donation note)
    f = aliasing.check_aliasing("ep", "c", {0}, [True, False],
                                [{"output": "0", "param": 0,
                                  "kind": "may-alias"}])
    assert not [x for x in f if x.severity == "error"]


def test_a1_injected_nondonated_alias_gates():
    # synthetic compiled header whose alias map reuses operand 1, which is
    # NOT donated — executable and declaration disagree (tampering or
    # registry rot); must be an error, not an info
    header = "HloModule m, input_output_alias={ {0}: (1, {}, may-alias) }"
    entries = aliasing.parse_input_output_alias(header)
    f = aliasing.check_aliasing("ep", "c", {0}, [True, False], entries)
    errs = [x for x in f if x.severity == "error"]
    assert _codes(errs) == ["nondonated-alias"]


def test_a1_pr4_reconstruction_on_a_real_jit():
    """Recreate PR 4's bug shape end to end: a jit whose declared donation
    disagrees with the caller contract must fail the pass, using the real
    lowering/compile artifacts (not synthetic text)."""
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(x, y):
        return x + y, y * 2.0

    lowered = step.lower(_sds((8, 8), jnp.float32), _sds((8, 8), jnp.float32))
    flags = aliasing.donated_operand_flags(lowered)
    assert flags == [True, False]
    entries = aliasing.parse_input_output_alias(lowered.compile().as_text())
    assert any(e["param"] == 0 for e in entries), \
        "compiled executable did not alias the donated operand"

    # correct contract: no errors
    ok = aliasing.check_aliasing("fx", "base", {0}, flags, entries)
    assert not [x for x in ok if x.severity == "error"]
    # the PR 4 setup: contract says y is donated too — host would drop it
    bad = aliasing.check_aliasing("fx", "base", {0, 1}, flags, entries)
    assert "donation-contract-mismatch" in _codes(
        [x for x in bad if x.severity == "error"])
    # the dual: contract retains x while the jit retires it
    bad = aliasing.check_aliasing("fx", "base", set(), flags, entries)
    assert "donation-contract-mismatch" in _codes(
        [x for x in bad if x.severity == "error"])


def test_a1_inert_donation_is_info_not_error():
    f = aliasing.check_aliasing("ep", "c", {0}, [True], [])
    assert [(x.severity, x.code) for x in f] == [("info", "donated-unaliased")]


# -- layer 2: A2, the wide-dtype / host-callback audit ------------------------

def test_a2_tensor_dtypes_ignore_attribute_i64():
    # `dimension = 1 : i64` is an ATTRIBUTE type every StableHLO module
    # carries; only tensor element types may gate
    text = """
    func.func public @main(%arg0: tensor<64x48xf32>) -> tensor<4xi32> {
      %0 = stablehlo.iota dim = 0 : tensor<4xi32>
      %1 = stablehlo.reduce(%arg0) {dimensions = array<i64: 1>} : tensor<?xf32>
      %2 = stablehlo.constant dense<1> : tensor<i1>
    }"""
    assert ir.module_tensor_dtypes(text) == {"f32", "i32", "i1"}
    assert ir.scan_module("ep", "c", text) == []


def test_a2_wide_dtype_and_callback_gate():
    text = """
      %0 = stablehlo.convert %arg0 : (tensor<4xi32>) -> tensor<4xi64>
      %1 = stablehlo.constant dense<0.0> : tensor<2x2xf64>
      %2 = stablehlo.custom_call @xla_python_cpu_callback(%arg1)
           {call_target_name = "xla_python_cpu_callback"} : tensor<4xf32>
    """
    f = ir.scan_module("ep", "c", text)
    assert _codes(f) == ["host-callback", "wide-dtype"]
    assert all(x.severity == "error" for x in f)
    wide = next(x for x in f if x.code == "wide-dtype")
    assert "f64" in wide.message and "i64" in wide.message


def test_a2_nested_complex_f64_detected():
    text = "%0 = fft %a : tensor<2xcomplex<f64>>"
    assert "c128" in ir.module_tensor_dtypes(text)
    assert _codes(ir.scan_module("ep", "c", text)) == ["wide-dtype"]


# -- layer 2: A3, the collective audit ----------------------------------------

def test_a3_panel_sized_and_disallowed_collectives_gate():
    panel_bytes = 64 * 48 * 4
    s = collectives.audit_hlo(
        "%all-gather.1 = f32[64,48]{1,0} all-gather(f32[64,24]{1,0} %p0)")
    f = collectives.check_collectives(
        "ep", "mesh4x2", s, allow=frozenset({"all-reduce"}),
        panel_bytes=panel_bytes, gather_budget=1024)
    assert _codes(f) == ["collective-kind", "full-panel-collective",
                         "gather-over-budget"]
    # a bounded reduce inside the allowlist is clean
    s = collectives.audit_hlo(
        "%all-reduce.1 = f32[14,14]{1,0} all-reduce(f32[14,14]{1,0} %p1)")
    f = collectives.check_collectives(
        "ep", "mesh4x2", s, allow=frozenset({"all-reduce"}),
        panel_bytes=panel_bytes, gather_budget=1024)
    assert f == []


# -- layer 2: A4, the recompile surface ---------------------------------------

def _ladder_cells(idx_dtype=jnp.int32, buckets=(8, 32, 128), n=3):
    return [Cell(f"bucket{b}",
                 (_sds((b, 9), jnp.float32), _sds((b,), idx_dtype)),
                 {"n": n}, role="ladder", bucket=b)
            for b in buckets]


def test_a4_clean_ladder_has_one_key_per_bucket():
    cells = _ladder_cells()
    assert surface.check_ladder("q", "query", cells) == []
    assert len({surface.cache_key(c) for c in cells}) == len(cells)


def test_a4_s64_retrace_trap_caught():
    """PR 1's incident: one rung's index operand drifts to the platform
    default i64 (np.arange vs the pad path's pinned i32) — same shapes,
    different dtype signature, a whole extra compile per bucket."""
    cells = _ladder_cells()[:2] + _ladder_cells(idx_dtype=jnp.int64,
                                                buckets=(128,))
    f = surface.check_ladder("q", "query", cells)
    assert "ladder-dtype-drift" in _codes(f)
    assert any("retrace" in x.message for x in f)


def test_a4_duplicate_collision_static_and_fixed_point():
    f = surface.check_ladder("q", "query",
                             _ladder_cells(buckets=(8, 8)))
    assert "duplicate-bucket" in _codes(f)
    assert "bucket-key-collision" in _codes(f)

    drift = _ladder_cells(buckets=(8,)) + _ladder_cells(buckets=(32,), n=4)
    f = surface.check_ladder("q", "query", drift)
    assert _codes(f) == ["ladder-static-drift"]

    f = surface.check_ladder("q", "query", _ladder_cells(buckets=(8, 100)))
    assert "bucket-not-fixed-point" in _codes(f)   # bucket_for(100) == 128


def test_a4_registered_ladders_declare_the_production_buckets():
    """The exact-arity contract on the real registry: query/scenario ride
    bucket_for's 8*4^i ladder, eigen rides draw_bucket's pow2 >= 64 —
    and every ladder's rungs map 1:1 onto distinct jit cache keys."""
    expected = {"query": (8, 32, 128), "scenario": (8, 32, 128),
                "eigen": (64, 128, 256)}
    seen = set()
    for ep in registry():
        if ep.ladder is None:
            continue
        seen.add(ep.ladder)
        rungs = [c for c in ep.cells() if c.role == "ladder"]
        assert tuple(c.bucket for c in rungs) == expected[ep.ladder], ep.name
        assert len({surface.cache_key(c) for c in rungs}) == len(rungs)
        assert surface.check_ladder(ep.name, ep.ladder, rungs) == []
    assert seen == set(expected)


# -- layer 2: A5, the static memory budgets -----------------------------------

def _budget_doc(cells):
    return {"schema": budgets.BUDGETS_SCHEMA, "tolerance": 0.25,
            "cells": cells}


def test_a5_measure_cell_workspace_nets_out_donation():
    mem = {"temp_bytes": 10, "argument_bytes": 100, "output_bytes": 50,
           "alias_bytes": 40, "generated_code_size_in_bytes": 999}
    assert budgets.measure_cell(mem) == {"temp_bytes": 10,
                                         "workspace_bytes": 120}


def test_a5_over_stale_unbudgeted_and_floor():
    doc = _budget_doc({
        "a/over": {"temp_bytes": 1_000_000, "workspace_bytes": 1_000_000},
        "a/stale": {"temp_bytes": 4_000_000, "workspace_bytes": 4_000_000},
        "a/tiny": {"temp_bytes": 1_000, "workspace_bytes": 1_000},
        "a/gone": {"temp_bytes": 1, "workspace_bytes": 1},
    })
    measured = {
        "a/over": {"temp_bytes": 2_000_000, "workspace_bytes": 1_000_000},
        "a/stale": {"temp_bytes": 1_000_000, "workspace_bytes": 4_000_000},
        # 5x over budget but under the 64 KiB floor: allocator jitter,
        # not a regression
        "a/tiny": {"temp_bytes": 5_000, "workspace_bytes": 5_000},
        "a/new": {"temp_bytes": 1, "workspace_bytes": 1},
    }
    f = budgets.check_budgets(measured, doc)
    got = {(x.code, x.severity) for x in f}
    assert got == {("over-temp_bytes", "error"),
                   ("stale-temp_bytes", "warn"),
                   ("unbudgeted", "error"),
                   ("stale-budget", "error")}


def test_a5_committed_budgets_cover_exactly_the_budgeted_cells():
    """Primary AND mesh cells carry budgets (ladder cells stay A4-only).
    Mesh cells keep their name/role even when the process has too few
    devices to compile them, so this set is environment-independent."""
    doc = budgets.load_budgets()
    assert doc["schema"] == budgets.BUDGETS_SCHEMA
    budgeted = {f"{ep.name}/{c.name}" for ep in registry()
                for c in ep.cells() if c.role in ("primary", "mesh")}
    assert set(doc["cells"]) == budgeted


# -- layer 3: registry completeness -------------------------------------------

def test_registry_covers_every_package_jit_root():
    """mfmlint's call graph enumerates every jit/pjit compilation unit in
    the package; each must be a registered audit entrypoint or carry a
    reviewed justification in NON_ENTRYPOINT_JITS — and neither list may
    go stale."""
    from mfm_tpu.lint import REPO_ROOT, Linter, collect_files

    lint = Linter()
    for f in collect_files(["mfm_tpu"], REPO_ROOT):
        lint.add_file(f, relto=REPO_ROOT)
    lint.build()
    roots = set(lint.jit_roots)
    assert roots, "call graph found no jit roots — linter regression?"

    registered = {ep.qualname for ep in registry()}
    justified = set(NON_ENTRYPOINT_JITS)
    assert not registered & justified, "a qualname cannot be both"
    missing = roots - registered - justified
    assert not missing, (
        f"jit roots with neither an audit registration nor a justification:"
        f" {sorted(missing)} — register them in mfm_tpu/analysis/registry.py"
        f" or add a reviewed NON_ENTRYPOINT_JITS entry")
    ghosts = (registered | justified) - roots
    assert not ghosts, (
        f"registry/justification entries that are no longer jit roots: "
        f"{sorted(ghosts)} — remove the stale entries")


def test_every_thread_target_is_sync_analyzed_or_justified():
    """The concurrency analogue of the jit-root test: every
    ``threading.Thread(target=...)`` spawned anywhere in the package must
    resolve to a method of a class mfmsync reasons about (one owning a
    lock or queue field, directly or by inheritance), or carry a
    reviewed rule-"S4" justification in tools/mfmsync_baseline.json —
    and neither list may go stale."""
    from pathlib import Path

    from mfm_tpu.analysis.sync import (
        DEFAULT_BASELINE, REPO_ROOT, load_baseline, run_sync)

    res = run_sync()
    covered, uncovered = res.analyzer.thread_target_coverage()
    assert covered, "no thread targets found — analyzer regression?"
    # the four known spawn sites: frontend write loop, the two protocol
    # readers (one IfExp site), frontend serve, coalescer flush loop
    quals = {rec["target"] for rec in covered}
    for must in ("_Conn._write_loop", "SocketFrontend.serve",
                 "Coalescer._flush_loop"):
        assert any(q and q.endswith(must) for q in quals), \
            f"lost track of the {must} thread spawn"

    baseline = load_baseline(
        str(Path(REPO_ROOT) / DEFAULT_BASELINE))
    justified = {(b["file"], b["qualname"]) for b in baseline
                 if b["rule"] == "S4"}
    needs = {(rec["file"], rec["target"] or rec["expr"])
             for rec in uncovered}
    missing = needs - justified
    assert not missing, (
        f"thread targets outside any mfmsync-analyzed class with no S4 "
        f"justification: {sorted(missing)} — give the target's class a "
        f"lock, or add a justified S4 entry to tools/mfmsync_baseline.json")
    ghosts = justified - needs
    assert not ghosts, (
        f"stale S4 baseline entries (targets now covered or gone): "
        f"{sorted(ghosts)} — remove them")


def test_registry_by_name_and_donation_contracts():
    ep = registry_by_name("risk.fused")
    assert ep.donate == (0, 1, 2, 3, 4)
    with pytest.raises(KeyError):
        registry_by_name("no.such.entrypoint")


# -- layer 1: the real tree ---------------------------------------------------

def test_full_audit_is_strict_clean_device_free_and_fast():
    assert jax.default_backend() == "cpu"   # lowering-only, by construction
    rep = run_audit()
    assert not rep.errors, "\n".join(f.message for f in rep.errors)
    assert rep.strict_clean
    assert rep.wall_s < 120, f"audit blew its device-free budget: {rep.wall_s}"
    # measured cells match the committed budget file exactly
    assert set(rep.measured) == set(budgets.load_budgets()["cells"])
    # mesh evidence is present and inside the fused step's allowlist
    mesh = rep.cells.get("risk.fused/mesh4x2")
    assert mesh is not None and mesh["compiled"]
    kinds = set(mesh["collectives"]["by_kind"])
    assert kinds and kinds <= {"all-reduce", "all-gather"}
    # production f32 mode: no wide dtype anywhere in the lowered evidence
    for key, entry in rep.cells.items():
        if "tensor_dtypes" in entry:
            assert not ({"f64", "i64"} & set(entry["tensor_dtypes"])), key


def test_audit_cli_surface_pass_only_is_cheap_and_clean():
    assert audit_main(["--passes", "A4"]) == 0


def test_audit_baseline_suppression_and_stale_detection():
    fake = [{"key": "A4:ghost.ep:ladder:empty-ladder", "note": "test"}]
    rep = run_audit(passes=("A4",), baseline=fake)
    assert rep.stale_baseline == ["A4:ghost.ep:ladder:empty-ladder"]
    assert not rep.strict_clean   # stale baseline fails --strict


# -- layer 4: the committed snapshot and the doctor ---------------------------

def test_committed_snapshot_verifies():
    snap = latest_snapshot_path()
    assert snap, "no committed AUDIT_r*.json"
    problems, _warns, doc = verify_snapshot(snap)
    assert problems == [], problems
    assert doc["strict_clean"]


def test_tampered_and_torn_snapshots_fail(tmp_path):
    snap = latest_snapshot_path()
    doc = json.load(open(snap, encoding="utf-8"))

    # tamper: delete the findings but keep the old seal
    forged = dict(doc, findings=[])
    p = tmp_path / "forged.json"
    p.write_text(json.dumps(forged))
    problems, _, _ = verify_snapshot(str(p))
    assert any("seal digest mismatch" in m for m in problems)

    # re-sealing a forged summary is caught by the strict-clean check
    lying = dict(doc, strict_clean=False)
    lying["sha256"] = report_digest(lying)
    p2 = tmp_path / "lying.json"
    p2.write_text(json.dumps(lying))
    problems, _, _ = verify_snapshot(str(p2))
    assert any("NOT strict-clean" in m for m in problems)

    # torn mid-write: unparseable, reported as a problem (not a crash)
    p3 = tmp_path / "torn.json"
    p3.write_text(json.dumps(doc)[: len(json.dumps(doc)) // 2])
    problems, _, d = verify_snapshot(str(p3))
    assert d is None and problems


def test_doctor_audit_exit_codes(tmp_path, capsys):
    from mfm_tpu.cli import main as cli_main

    with pytest.raises(SystemExit) as e:
        cli_main(["doctor", "--audit"])
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out)["records"][0]
    assert rec["kind"] == "audit_snapshot" and rec["status"] == "ok"

    torn = tmp_path / "torn.json"
    torn.write_text('{"schema": "mfmaudit/1", "cells": {')
    with pytest.raises(SystemExit) as e:
        cli_main(["doctor", "--audit", str(torn)])
    assert e.value.code == 1
    rec = json.loads(capsys.readouterr().out)["records"][0]
    assert rec["status"] == "corrupt"

    # doctor without a path and without --audit refuses with guidance
    with pytest.raises(SystemExit) as e:
        cli_main(["doctor"])
    assert "PATH is required" in str(e.value)


def test_findings_key_schema_is_stable():
    f = Finding("A1", "error", "risk.fused", "base", "nondonated-alias", "m")
    assert f.key() == "A1:risk.fused:base:nondonated-alias"
    assert f.to_dict()["severity"] == "error"
