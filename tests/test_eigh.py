"""Correctness of the batched Jacobi eigh (pure-JAX Brent-Luk path).

The Pallas TPU kernel shares the same schedule/rotation math and is
exercised on real TPU hardware by bench.py; these tests pin the algorithm
against LAPACK on CPU, including odd sizes and degenerate spectra.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.ops.eigh import (
    _brent_luk_perms,
    batched_eigh,
    batched_eigh_weighted_diag,
    canonicalize_signs,
    jacobi_eigh,
)


def _random_sym(rng, B, n):
    A = rng.standard_normal((B, n, n))
    return (A + A.transpose(0, 2, 1)) / 2


@pytest.mark.parametrize("n", [2, 5, 8, 42, 43])
def test_jacobi_matches_lapack(n):
    rng = np.random.default_rng(0)
    A = _random_sym(rng, 20, n)
    w, V = jax.jit(jacobi_eigh)(jnp.asarray(A))
    w, V = np.asarray(w), np.asarray(V)
    wr = np.linalg.eigh(A)[0]
    np.testing.assert_allclose(w, wr, rtol=1e-10, atol=1e-12)
    R = np.einsum("bij,bj,bkj->bik", V, w, V)
    np.testing.assert_allclose(R, A, atol=1e-11)
    I = np.einsum("bij,bik->bjk", V, V)
    np.testing.assert_allclose(I, np.broadcast_to(np.eye(n), I.shape), atol=1e-12)


def test_schedule_covers_all_pairs():
    # asserts full pair coverage AND that pi has order n-1 (the Pallas
    # kernel emits outputs through argsort(b0) relying on the latter)
    from mfm_tpu.ops.eigh import _check_perm_schedule

    for n in (4, 6, 42, 64):
        _check_perm_schedule(n)


def test_degenerate_spectrum_and_diagonal():
    # repeated eigenvalues and an already-diagonal matrix
    A = np.stack([
        np.diag([3.0, 3.0, 1.0, 1.0]),
        np.diag([2.0, 2.0, 2.0, 2.0]),
    ])
    w, V = jacobi_eigh(jnp.asarray(A))
    np.testing.assert_allclose(np.asarray(w), np.sort(np.diagonal(A, axis1=1, axis2=2)),
                               atol=1e-14)
    R = np.einsum("bij,bj,bkj->bik", np.asarray(V), np.asarray(w), np.asarray(V))
    np.testing.assert_allclose(R, A, atol=1e-13)


def test_psd_rank_deficient():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((10, 42, 5))
    A = X @ X.transpose(0, 2, 1)  # rank 5 PSD
    w, V = jax.jit(jacobi_eigh)(jnp.asarray(A))
    w = np.asarray(w)
    wr = np.linalg.eigh(A)[0]
    np.testing.assert_allclose(w, wr, rtol=1e-8, atol=1e-10)
    assert np.all(w[:, :37] < 1e-9)  # 37 (near-)zero eigenvalues


def test_canonical_signs_deterministic():
    rng = np.random.default_rng(1)
    A = _random_sym(rng, 5, 8)
    w1, V1 = jacobi_eigh(jnp.asarray(A))
    w2, V2 = canonicalize_signs(*jnp.linalg.eigh(jnp.asarray(A)))
    np.testing.assert_allclose(np.asarray(V1), np.asarray(V2), atol=1e-10)


def test_batched_eigh_dispatcher_cpu():
    rng = np.random.default_rng(2)
    A = _random_sym(rng, 7, 10)
    w, V = batched_eigh(jnp.asarray(A))
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigh(A)[0], atol=1e-12)


def test_batched_eigh_dispatch_is_lowering_time_not_trace_time(monkeypatch):
    """The Pallas-vs-XLA choice must be made by ``lax.platform_dependent``
    at lowering time, NOT by querying ``jax.devices()`` during tracing.

    The trace-time query once baked the process-default backend into the
    program: a TPU-attached process jitting onto a virtual CPU mesh (the
    driver's ``dryrun_multichip`` gate running after ``entry()`` in the same
    process) selected the Pallas branch and died with "Only interpret mode
    is supported on CPU backend".  Poisoning ``jax.devices`` proves no
    trace-time query remains; the jitted call still runs on CPU because the
    platform resolves during lowering.
    """
    from mfm_tpu.ops import eigh as eigh_mod

    def _boom(*a, **k):
        raise AssertionError("trace-time jax.devices() query in eigh dispatch")

    monkeypatch.setattr(eigh_mod.jax, "devices", _boom)
    rng = np.random.default_rng(3)
    A = rng.standard_normal((4, 6, 6)).astype(np.float32)
    A = jnp.asarray(A + np.swapaxes(A, -1, -2))
    d0 = jnp.asarray(np.abs(rng.standard_normal((4, 6))).astype(np.float32))

    w, _ = jax.jit(lambda A: batched_eigh(A))(A)
    np.testing.assert_allclose(
        np.asarray(w), np.linalg.eigh(np.asarray(A, np.float64))[0],
        rtol=1e-5, atol=1e-6)
    w2, h2 = jax.jit(batched_eigh_weighted_diag)(A, d0)
    wr, Vr = np.linalg.eigh(np.asarray(A, np.float64))
    order = np.argsort(np.asarray(w2), axis=-1)
    np.testing.assert_allclose(
        np.take_along_axis(np.asarray(h2), order, -1),
        np.einsum("...ki,...k->...i", Vr**2, np.asarray(d0, np.float64)),
        rtol=1e-4, atol=1e-5)


def test_platform_dependent_lowerings_pick_the_right_branch():
    """Hardware-free proof that the lowering-time dispatch picks the Pallas
    kernel on TPU and the XLA eigh on CPU: AOT-export the same jitted
    function for each platform from this CPU-only host and look for the
    Mosaic custom call in the lowered module.  Catches both regressions the
    dispatch rework could introduce — the ``tpu=`` branch not matching the
    TPU lowering platform (silent ~8x eigen slowdown) and the Pallas branch
    leaking into CPU programs (driver-gate lowering failure)."""
    from jax import export

    # the suite conftest enables x64 for golden parity; Mosaic lowering
    # rejects the weak-f64 literals that mode creates, and production
    # (pipeline fast path) runs with x64 off anyway
    from jax.experimental import disable_x64

    with disable_x64():
        A = jnp.asarray(np.eye(42, dtype=np.float32)[None].repeat(2, 0))
        f = jax.jit(lambda A: batched_eigh(A))
        tpu_mod = str(export.export(f, platforms=("tpu",))(A).mlir_module())
        assert "tpu_custom_call" in tpu_mod
        cpu_mod = str(export.export(f, platforms=("cpu",))(A).mlir_module())
        assert "tpu_custom_call" not in cpu_mod
        assert "eigh" in cpu_mod or "custom_call" in cpu_mod


def test_explicit_pallas_pin_on_ineligible_shape_raises():
    """An explicit ``prefer_pallas=True`` on a shape/dtype the kernel cannot
    run (odd n, n > 128, f64) must raise, not silently measure XLA — the
    same no-silent-fallback rule bench.py applies to platform pins."""
    rng = np.random.default_rng(5)
    A_odd = rng.standard_normal((2, 7, 7)).astype(np.float32)
    A_odd = jnp.asarray(A_odd + np.swapaxes(A_odd, -1, -2))
    with pytest.raises(ValueError, match="prefer_pallas=True"):
        batched_eigh(A_odd, prefer_pallas=True)
    A_f64 = jnp.asarray(np.eye(6)[None].astype(np.float64))
    with pytest.raises(ValueError, match="prefer_pallas=True"):
        batched_eigh_weighted_diag(A_f64, jnp.ones((1, 6)),
                                   prefer_pallas=True)


def test_pallas_kernel_interpret_matches_lapack():
    """Pin the Pallas kernel's fused rotation+permutation math on CPU via
    interpret mode (the TPU-compiled path runs the identical kernel)."""
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

    rng = np.random.default_rng(4)
    n = 42
    X = rng.standard_normal((3, n, n)).astype(np.float32)
    A = np.einsum("bik,bjk->bij", X, X) / n  # PSD, the risk-model case
    w, V = jacobi_eigh_tpu(jnp.asarray(A), interpret=True)
    w, V = np.asarray(w, np.float64), np.asarray(V, np.float64)
    wr = np.linalg.eigh(A.astype(np.float64))[0]
    np.testing.assert_allclose(w, wr, rtol=2e-4, atol=1e-5)
    R = np.einsum("bij,bj,bkj->bik", V, w, V)
    np.testing.assert_allclose(R, A, atol=5e-5)
    I = np.einsum("bij,bik->bjk", V, V)
    np.testing.assert_allclose(I, np.broadcast_to(np.eye(n), I.shape), atol=1e-5)


def test_pallas_kernel_reduced_sweeps_match_default_on_sim_matrices():
    """Pin the production eigen_sim_sweeps="auto" claim: on stage-realistic
    scaled-Wishart G = diag(s) C diag(s) matrices (models/eigen.py), the
    reduced sweep count matches the solver default — eigenvalues bitwise
    (converged rotations are exact no-ops), eigenvectors to last-bit f32
    noise on near-degenerate pairs (a convergence regression like 4 sweeps
    shows up at ~8e-3 kernel residual, four orders above this gate)."""
    from mfm_tpu.models.eigen import sim_sweeps_for
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

    rng = np.random.default_rng(6)
    n, M = 42, 4
    d = rng.standard_normal((M, n, 200)).astype(np.float32)
    d -= d.mean(axis=-1, keepdims=True)
    C = np.einsum("mkt,mlt->mkl", d, d) / (200 - 1)
    s = np.abs(rng.normal(0.02, 0.01, n)).astype(np.float32)
    G = jnp.asarray(s[None, :, None] * C * s[None, None, :])

    few = sim_sweeps_for(n, jnp.float32, sim_length=200)
    w5, V5 = jacobi_eigh_tpu(G, sweeps=few, canonical_signs=False,
                             sort=False, interpret=True)
    w7, V7 = jacobi_eigh_tpu(G, canonical_signs=False, sort=False,
                             interpret=True)
    np.testing.assert_array_equal(np.asarray(w5), np.asarray(w7))
    np.testing.assert_allclose(np.asarray(V5), np.asarray(V7), atol=3e-7)


def test_pallas_kernel_interpret_unsorted_consistent_pairs():
    """sort=False still pairs each eigenvalue with its eigenvector."""
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

    rng = np.random.default_rng(5)
    n = 20
    X = rng.standard_normal((2, n, n)).astype(np.float32)
    A = np.einsum("bik,bjk->bij", X, X) / n
    w, V = jacobi_eigh_tpu(jnp.asarray(A), canonical_signs=False, sort=False,
                           interpret=True)
    w, V = np.asarray(w, np.float64), np.asarray(V, np.float64)
    R = np.einsum("bij,bj,bkj->bik", V, w, V)
    np.testing.assert_allclose(R, A, atol=5e-5)


def test_pallas_kernel_unsorted_slots_follow_original_indices():
    """sort=False slot order contract (ops/eigh_pallas.py): for near-diagonal
    input, the eigenvalue tracking diagonal direction i lands at slot i — NOT
    in the kernel's internal Brent-Luk interleaved basis order.  The eigen
    Monte-Carlo pairs slot i's bias with D0[i], so a scrambled slot order
    silently mispairs every direction's bias."""
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

    rng = np.random.default_rng(7)
    n = 16
    d = np.linspace(1.0, 16.0, n).astype(np.float32)  # well-separated, ascending
    E = 0.01 * rng.standard_normal((3, n, n)).astype(np.float32)
    A = np.stack([np.diag(d)] * 3) + (E + E.transpose(0, 2, 1)) / 2
    w, V = jacobi_eigh_tpu(jnp.asarray(A), canonical_signs=False, sort=False,
                           interpret=True)
    # each slot's eigenvalue stays within the perturbation of its diagonal
    np.testing.assert_allclose(np.asarray(w), np.stack([d] * 3), atol=0.1)

    # rank-deficiency >= 2: exact zero rows/cols at indices 0 and 1 must
    # produce exact zeros at SLOTS 0 and 1 (the pre-fix interleaved order put
    # the second zero at slot 2, deflating a nonzero direction's eigenvalue)
    G = np.diag(np.array([0.0, 0.0] + list(1.0 + np.arange(n - 2)),
                         np.float32))
    E2 = 0.001 * rng.standard_normal((n - 2, n - 2)).astype(np.float32)
    G[2:, 2:] += (E2 + E2.T) / 2  # perturb the nonzero block only
    w0, _ = jacobi_eigh_tpu(jnp.asarray(G)[None], canonical_signs=False,
                            sort=False, interpret=True)
    w0 = np.asarray(w0[0])
    assert w0[0] == 0.0 and w0[1] == 0.0
    assert (w0[2:] > 0.5).all()


def test_production_sim_sweeps_deep_tier_accuracy():
    """The deep near-diagonal tier (sim_length >= 32K -> default-3 sweeps,
    models/eigen.py::sim_sweeps_for): at K=42, 1390 draws the sweep
    reduction must stay well under the 1e-5 parity contract (measured
    1.5e-6 in the final adjusted covariance on TPU; 3 sweeps is 5e-5)."""
    from mfm_tpu.models.eigen import sim_sweeps_for
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

    rng = np.random.default_rng(7)
    n, M, L = 42, 3, 1390
    d = rng.standard_normal((M, n, L)).astype(np.float32)
    d -= d.mean(axis=-1, keepdims=True)
    C = np.einsum("mkt,mlt->mkl", d, d) / (L - 1)
    s = np.abs(rng.normal(0.02, 0.01, n)).astype(np.float32)
    G = jnp.asarray(s[None, :, None] * C * s[None, None, :])

    few = sim_sweeps_for(n, jnp.float32, sim_length=L)
    wf, _ = jacobi_eigh_tpu(G, sweeps=few, canonical_signs=False,
                            sort=False, interpret=True)
    w7, _ = jacobi_eigh_tpu(G, canonical_signs=False, sort=False,
                            interpret=True)
    wf = np.sort(np.asarray(wf), axis=-1)
    w7 = np.sort(np.asarray(w7), axis=-1)
    assert np.abs(wf - w7).max() <= 1e-5 * np.abs(w7).max()


def test_weighted_diag_kernel_matches_full_kernel_plus_einsum():
    """The fused (w, h) kernel must reproduce the unfused path exactly: same
    rotations, h computed from the same in-VMEM V that jacobi_eigh_tpu would
    have written out (models/eigen.py's Dm_hat consumer)."""
    from mfm_tpu.ops.eigh_pallas import (
        jacobi_eigh_tpu,
        jacobi_eigh_weighted_diag_tpu,
    )

    rng = np.random.default_rng(11)
    n, B = 8, 5
    X = rng.standard_normal((B, 16, n)).astype(np.float32)
    A = jnp.asarray(np.einsum("bnk,bnl->bkl", X, X) / 16)
    d0 = jnp.asarray(np.abs(rng.standard_normal((B, n))).astype(np.float32))

    w_ref, V_ref = jacobi_eigh_tpu(A, canonical_signs=False, sort=False,
                                   interpret=True)
    h_ref = jnp.einsum("bki,bk->bi", V_ref * V_ref, d0)
    w, h = jacobi_eigh_weighted_diag_tpu(A, d0, interpret=True)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w_ref))
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref),
                               rtol=1e-6, atol=1e-7)


def test_batched_eigh_weighted_diag_fallback_matches_loopy():
    """The non-Pallas dispatcher path (CPU / f64): eigh + einsum vs a loopy
    per-matrix NumPy computation, including batch-broadcast d0."""
    from mfm_tpu.ops.eigh import batched_eigh_weighted_diag

    rng = np.random.default_rng(12)
    T, M, n = 3, 4, 6
    X = rng.standard_normal((T, M, 12, n))
    A = np.einsum("tmnk,tmnl->tmkl", X, X) / 12
    d0 = np.abs(rng.standard_normal((T, n)))

    w, h = batched_eigh_weighted_diag(
        jnp.asarray(A), jnp.asarray(d0)[:, None, :], prefer_pallas=False)
    for t in range(T):
        for m in range(M):
            wr, Vr = np.linalg.eigh(A[t, m])
            hr = (Vr**2 * d0[t][:, None]).sum(axis=0)
            order = np.argsort(np.asarray(w[t, m]))
            np.testing.assert_allclose(np.asarray(w[t, m])[order], wr,
                                       rtol=1e-10, atol=1e-12)
            np.testing.assert_allclose(np.asarray(h[t, m])[order], hr,
                                       rtol=1e-8, atol=1e-10)


def test_pinv_psd_matches_numpy_pinv():
    """Eigh-based PSD pseudo-inverse (the regression stage's solver) vs
    np.linalg.pinv, including rank-deficient, odd-n (padded), and zero
    matrices."""
    from mfm_tpu.ops.eigh import pinv_psd

    rng = np.random.default_rng(21)
    for n, rank in ((41, 41), (41, 30), (6, 6), (6, 3)):
        X = rng.standard_normal((5, rank, n))
        G = np.einsum("bri,brj->bij", X, X)
        got = np.asarray(pinv_psd(jnp.asarray(G), prefer_pallas=False))
        ref = np.linalg.pinv(G)
        np.testing.assert_allclose(got, ref, rtol=5e-9, atol=1e-10)
    # zero matrix -> zero pseudo-inverse
    Z = jnp.zeros((2, 5, 5))
    np.testing.assert_array_equal(np.asarray(pinv_psd(Z)), np.zeros((2, 5, 5)))


def test_weighted_diag_kernel_vt_rows_layout_matches():
    """The transposed-eigenvector (rows-pass) layout of the weighted kernel
    is an internal VMEM layout choice and must produce identical (w, h)."""
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu

    rng = np.random.default_rng(22)
    n, B = 8, 5
    X = rng.standard_normal((B, 16, n)).astype(np.float32)
    A = jnp.asarray(np.einsum("bnk,bnl->bkl", X, X) / 16)
    d0 = jnp.asarray(np.abs(rng.standard_normal((B, n))).astype(np.float32))

    w0, h0 = jacobi_eigh_weighted_diag_tpu(A, d0, interpret=True)
    w1, h1 = jacobi_eigh_weighted_diag_tpu(A, d0, vt_rows=True,
                                           interpret=True)
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
    np.testing.assert_allclose(np.asarray(h0), np.asarray(h1),
                               rtol=1e-6, atol=1e-7)


def test_weighted_diag_kernel_interpret_parity_vs_xla():
    """Direct parity of the fused Pallas weighted-diag kernel against the
    XLA dispatch path (eigh + einsum) — the two sides of the
    batched_eigh_weighted_diag backend decision.  Slot orders differ by
    contract (original-index vs ascending), so the kernel outputs are
    rank-sorted before comparison; (w_i, h_i) pairing must survive it."""
    from mfm_tpu.ops.eigh import batched_eigh_weighted_diag
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu

    rng = np.random.default_rng(31)
    n, B = 8, 6
    X = rng.standard_normal((B, 16, n)).astype(np.float32)
    A = jnp.asarray(np.einsum("bnk,bnl->bkl", X, X) / 16)
    d0 = jnp.asarray(np.abs(rng.standard_normal((B, n))).astype(np.float32))

    # full sweep count on both sides: the XLA path's LAPACK eigh is fully
    # converged, so the kernel must run its converged (non-sim-capped) count
    w_ref, h_ref = batched_eigh_weighted_diag(A, d0, prefer_pallas=False)
    w, h = jacobi_eigh_weighted_diag_tpu(A, d0, interpret=True)
    order = jnp.argsort(w, axis=-1)
    w = jnp.take_along_axis(w, order, axis=-1)
    h = jnp.take_along_axis(h, order, axis=-1)
    np.testing.assert_allclose(np.asarray(w), np.asarray(w_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref),
                               rtol=1e-4, atol=1e-5)


def test_weighted_diag_kernel_rejects_odd_n():
    """n must be even (Brent-Luk adjacent pairing): a 7x7 batch is a
    ValueError naming the XLA fallback, not a shape crash inside the
    kernel — and the same contract holds for the unfused kernel."""
    from mfm_tpu.ops.eigh_pallas import (
        jacobi_eigh_tpu,
        jacobi_eigh_weighted_diag_tpu,
    )

    A = jnp.eye(7)[None].repeat(2, axis=0)
    d0 = jnp.ones((2, 7))
    with pytest.raises(ValueError, match="even n"):
        jacobi_eigh_weighted_diag_tpu(A, d0, interpret=True)
    with pytest.raises(ValueError, match="even n"):
        jacobi_eigh_tpu(A, interpret=True)


def test_weighted_diag_kernel_v_compose2_bitwise_identical():
    """The composed two-round vt update performs the SAME floating-point
    operations in the same order as two sequential vt row passes (only the
    intermediate restack disappears), so (w, h) must be bitwise equal —
    for both even (sweeps=4 -> 28 rounds) and odd (sweeps=7 -> 49 rounds,
    one trailing single round) round counts at n=8."""
    from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu

    rng = np.random.default_rng(23)
    n, B = 8, 5
    X = rng.standard_normal((B, 16, n)).astype(np.float32)
    A = jnp.asarray(np.einsum("bnk,bnl->bkl", X, X) / 16)
    d0 = jnp.asarray(np.abs(rng.standard_normal((B, n))).astype(np.float32))

    for sweeps in (4, 7):
        w0, h0 = jacobi_eigh_weighted_diag_tpu(
            A, d0, sweeps=sweeps, vt_rows=True, interpret=True)
        w1, h1 = jacobi_eigh_weighted_diag_tpu(
            A, d0, sweeps=sweeps, vt_rows=True, v_compose2=True,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
        np.testing.assert_array_equal(np.asarray(h0), np.asarray(h1))
