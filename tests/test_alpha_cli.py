"""Hermetic test for the ``alpha`` CLI driver (BASELINE config-5 surface)."""

import json

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.cli import main as cli_main


@pytest.fixture()
def panel_csv(tmp_path):
    rng = np.random.default_rng(0)
    T, N = 60, 10
    dates = pd.bdate_range("2023-01-02", periods=T)
    rows = []
    for j in range(N):
        close = np.exp(1 + np.cumsum(0.02 * rng.standard_normal(T)))
        ret = np.concatenate([[np.nan], close[1:] / close[:-1] - 1])
        vol = np.exp(rng.normal(10, 1, T))
        for t in range(T):
            if rng.random() < 0.05:
                continue  # holes exercise the next-traded-day shift
            rows.append({"ts_code": f"{600000+j}.SH", "trade_date": dates[t],
                         "close": close[t], "ret": ret[t], "volume": vol[t]})
    path = tmp_path / "panel.csv"
    pd.DataFrame(rows).to_csv(path, index=False)
    return str(path)


def test_alpha_cli_scores_expressions(panel_csv, tmp_path, capsys):
    exprs = tmp_path / "exprs.txt"
    exprs.write_text(
        "# candidate alphas\n"
        "cs_rank(delta(close, 3))\n"
        "\n"
        "-ts_corr(close, volume, 10)\n"
        "signed_power(cs_winsorize(ret, 2.5), 0.5)\n"
    )
    out = str(tmp_path / "scores.csv")
    cli_main(["alpha", "--exprs", str(exprs), "--panel", panel_csv,
              "--out", out])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["n_exprs"] == 3
    assert rec["stocks"] == 10

    score = pd.read_csv(out, index_col="expression")
    assert len(score) == 3
    for col in ("mean_ic", "ic_ir", "mean_rank_ic", "coverage",
                "mean_turnover", "mean_spread"):
        assert col in score.columns
    assert (score["coverage"] > 0.5).all()


def test_alpha_cli_reports_bad_expression_line(panel_csv, tmp_path):
    exprs = tmp_path / "exprs.txt"
    exprs.write_text("cs_rank(close)\n__import__('os')\n")
    with pytest.raises(SystemExit, match="exprs.txt:2"):
        cli_main(["alpha", "--exprs", str(exprs), "--panel", panel_csv])


def test_alpha_cli_unknown_fwd_field(panel_csv, tmp_path):
    exprs = tmp_path / "exprs.txt"
    exprs.write_text("cs_rank(close)\n")
    with pytest.raises(SystemExit, match="no field"):
        cli_main(["alpha", "--exprs", str(exprs), "--panel", panel_csv,
                  "--fwd-field", "nope"])


def test_alpha_cli_syntax_error_and_missing_field_diagnostics(panel_csv,
                                                              tmp_path):
    # raw Python syntax error still gets the file:line diagnostic
    exprs = tmp_path / "exprs.txt"
    exprs.write_text("cs_rank(close)\nclose +\n")
    with pytest.raises(SystemExit, match="exprs.txt:2"):
        cli_main(["alpha", "--exprs", str(exprs), "--panel", panel_csv])

    # a typo'd field fails up front with the line number, not a KeyError
    # from inside jit tracing
    exprs.write_text("cs_rank(vwap)\n")
    with pytest.raises(SystemExit, match="exprs.txt:1.*vwap"):
        cli_main(["alpha", "--exprs", str(exprs), "--panel", panel_csv])


def test_alpha_exprs_from_stdin(tmp_path, capsys, monkeypatch):
    import io

    from mfm_tpu.cli import main

    rng = np.random.default_rng(8)
    T, N = 30, 8
    dates = pd.bdate_range("2024-01-02", periods=T)
    stocks = [f"s{i}" for i in range(N)]
    close = np.exp(np.cumsum(0.02 * rng.standard_normal((T, N)), axis=0))
    pd.DataFrame({
        "trade_date": np.repeat(dates, N),
        "ts_code": np.tile(stocks, T),
        "close": close.ravel(),
        "ret": np.vstack([np.full((1, N), np.nan),
                          close[1:] / close[:-1] - 1]).ravel(),
    }).to_csv(tmp_path / "panel.csv", index=False)

    monkeypatch.setattr("sys.stdin",
                        io.StringIO("cs_rank(delta(close, 2))\n"
                                    "# a comment\n"
                                    "-ts_mean(ret, 3)\n"))
    main(["--platform", "cpu", "alpha", "--exprs", "-",
          "--panel", str(tmp_path / "panel.csv"),
          "--out", str(tmp_path / "scores.csv")])
    rec = json.loads(capsys.readouterr().out)
    assert rec["n_exprs"] == 2
    assert len(pd.read_csv(tmp_path / "scores.csv")) == 2
