"""Query-service request loop (mfm_tpu/serve/server.py): per-bit request
guards + dead-letter records, circuit-breaker transitions on an injected
clock, shed-oldest admission control, deadline expiry, degraded-serving
stamps, the end-to-end JSONL loop, and the `doctor --serve` audit."""

import io
import json
import os

import numpy as np
import pytest

from mfm_tpu.serve import (
    CircuitBreaker,
    QueryEngine,
    QueryServer,
    ServePolicy,
    parse_request,
    req_reason_names,
)
from mfm_tpu.serve.server import (
    REQ_REASON_DTYPE,
    REQ_REASON_NAN_WEIGHT,
    REQ_REASON_SCHEMA,
    REQ_REASON_SHORT_WEIGHTS,
    REQ_REASON_UNKNOWN_BENCHMARK,
    REQ_REASON_UNKNOWN_FACTOR,
    REQ_REASON_WEIGHT_OUTLIER,
)

K = 4


def _engine(staleness=0):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((K, K)) / 2
    cov = (a @ a.T + 1e-3 * np.eye(K)) * 1e-4
    return QueryEngine(cov, factor_names=["country", "ind0", "size", "mom"],
                       benchmarks={"idx": rng.standard_normal(K)},
                       staleness=staleness)


class Clock:
    """Injectable monotonic clock the breaker/deadline tests advance."""

    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _req(rid, w=None, **kw):
    return json.dumps({"id": rid,
                       "weights": [0.1] * K if w is None else w, **kw})


# -- request guards ----------------------------------------------------------

@pytest.mark.parametrize("line,bit", [
    ('{"id": "x", "weights": [0.1,', REQ_REASON_SCHEMA),       # torn json
    ('"not an object"', REQ_REASON_SCHEMA),
    (json.dumps({"id": "x"}), REQ_REASON_SCHEMA),              # no weights
    (_req("x", deadline_s=-1), REQ_REASON_SCHEMA),
    (_req("x", w=["a"] * K), REQ_REASON_DTYPE),
    (_req("x", w={"country": "NaNope"}), REQ_REASON_DTYPE),
    (_req("x", w=[0.1, float("nan"), 0.1, 0.1]), REQ_REASON_NAN_WEIGHT),
    (_req("x", w=[0.1]), REQ_REASON_SHORT_WEIGHTS),
    (_req("x", w=[[0.1] * K]), REQ_REASON_SHORT_WEIGHTS),      # 2-D
    (_req("x", w={"country": 1.0, "bogus": 2.0}), REQ_REASON_UNKNOWN_FACTOR),
    (_req("x", benchmark="nope"), REQ_REASON_UNKNOWN_BENCHMARK),
])
def test_parse_request_reason_bits(line, bit):
    fields, mask, detail = parse_request(line, _engine(), ServePolicy())
    assert mask & bit, f"expected bit {req_reason_names(bit)} in " \
        f"{req_reason_names(mask)} ({detail!r})"


def test_parse_request_weight_outlier_gated():
    # nonzero MAD needed: a constant cross-section disables the check
    line = _req("x", w=[0.1, 0.12, 0.09, 99.0])
    _, mask, _ = parse_request(line, _engine(), ServePolicy())
    assert mask == 0                      # mad_k=0: check disabled
    _, mask, _ = parse_request(line, _engine(),
                               ServePolicy(weight_mad_k=5.0))
    assert mask == REQ_REASON_WEIGHT_OUTLIER


def test_parse_request_dict_weights_and_benchmark():
    line = _req("x", w={"size": 0.7, "mom": 0.3}, benchmark="idx",
                deadline_s=2.5)
    fields, mask, _ = parse_request(line, _engine(), ServePolicy())
    assert mask == 0
    rid, w, bidx, deadline_s, scenario, trace_id, construct, sweep = fields
    assert rid == "x" and bidx == 1 and deadline_s == 2.5
    assert scenario is None and trace_id is None and construct is None
    assert sweep is None
    np.testing.assert_array_equal(w, [0.0, 0.0, 0.7, 0.3])


def test_dead_letter_records(tmp_path):
    dl = str(tmp_path / "dead.jsonl")
    server = QueryServer(_engine(), ServePolicy(), health="ok",
                         dead_letter_path=dl)
    out = server.submit_line(_req("bad", w=[1.0]))
    assert out[0]["outcome"] == "dead_letter"
    assert out[0]["reasons"] == ["short_weights"]
    server.close()
    rec, = [json.loads(ln) for ln in open(dl)]
    assert rec["id"] == "bad" and rec["reasons"] == ["short_weights"]
    assert rec["mask"] == REQ_REASON_SHORT_WEIGHTS and rec["line"]


# -- circuit breaker ---------------------------------------------------------

def test_breaker_full_cycle():
    clk = Clock()
    br = CircuitBreaker(failures=2, cooldown_s=5.0, clock=clk)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"           # 1 < threshold
    br.record_failure()
    assert br.state == "open" and br.open_reason == "failures"
    assert not br.allow()
    assert br.retry_after() == pytest.approx(5.0)
    clk.t += 5.0
    assert br.allow() and br.state == "half_open"   # one probe admitted
    br.record_success()
    assert br.state == "closed" and br.open_reason is None
    # half-open probe FAILURE re-opens immediately (no threshold count)
    br.record_failure()
    br.record_failure()
    clk.t += 5.0
    assert br.allow() and br.state == "half_open"
    br.record_failure()
    assert br.state == "open"


def test_breaker_force_open_rearms_cooldown():
    clk = Clock()
    br = CircuitBreaker(failures=3, cooldown_s=10.0, clock=clk)
    br.force_open("health_degraded")
    clk.t += 8.0
    br.force_open("fence_audit")          # re-armed: 10 s from NOW
    assert br.retry_after() == pytest.approx(10.0)
    assert br.open_reason == "fence_audit"


# -- admission control / deadlines ------------------------------------------

def test_shed_oldest_ordering():
    policy = ServePolicy(queue_max=4, batch_max=4, default_deadline_s=60.0)
    server = QueryServer(_engine(), policy, health="ok")
    buf = io.StringIO()
    lines = [_req(f"q{i}") for i in range(10)]
    server.run(iter(lines), buf, gulp=True)
    resps = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert [r["id"] for r in resps if r["outcome"] == "shed"] == \
        [f"q{i}" for i in range(6)]       # oldest first, in arrival order
    assert {r["id"] for r in resps if r["outcome"] == "ok"} == \
        {"q6", "q7", "q8", "q9"}          # the newest queue_max survive


def test_deadline_expiry_no_device_work():
    clk = Clock()
    server = QueryServer(_engine(), ServePolicy(default_deadline_s=60.0),
                         health="ok", clock=clk)
    server.submit_line(_req("fast", deadline_s=1.0))
    server.submit_line(_req("slow", deadline_s=100.0))
    clk.t += 2.0                          # "fast" dies in the queue
    out = {r["id"]: r for r in server.drain()}
    assert out["fast"]["outcome"] == "deadline" and not out["fast"]["ok"]
    assert out["slow"]["outcome"] == "ok"


# -- degraded serving --------------------------------------------------------

def test_degraded_stamps_and_breaker():
    clk = Clock()
    server = QueryServer(_engine(staleness=3), ServePolicy(),
                         health="degraded", clock=clk)
    # degraded health at construction force-opens the breaker
    resp, = server.submit_line(_req("r1"))
    assert resp["outcome"] == "rejected"
    assert resp["breaker"] == "health_degraded"
    assert resp["retry_after_s"] > 0
    assert resp["degraded"] is True and resp["staleness"] == 3


def test_swap_to_healthy_recovers_via_halfopen():
    clk = Clock()
    policy = ServePolicy(breaker_cooldown_s=5.0, default_deadline_s=60.0)
    server = QueryServer(_engine(staleness=3), policy, health="degraded",
                         clock=clk)
    server.swap(engine=_engine(staleness=0), health="ok")
    # recovery is NOT instant: the normal cooldown -> half-open path runs
    assert server.submit_line(_req("r1"))[0]["outcome"] == "rejected"
    clk.t += 5.0
    assert server.submit_line(_req("r2")) == []       # probe admitted
    ok, = server.drain()
    assert ok["outcome"] == "ok" and ok["degraded"] is False
    assert server.breaker.state == "closed"


def test_reload_fence_failure_opens_breaker():
    from mfm_tpu.data.artifacts import ArtifactCorruptError

    def reload_fn():
        raise ArtifactCorruptError("checksum mismatch")

    server = QueryServer(_engine(), ServePolicy(default_deadline_s=60.0),
                         health="ok", reload_fn=reload_fn)
    server.submit_line(_req("r1"))
    server.poll_reload()
    assert server.breaker.state == "open"
    assert server.breaker.open_reason == "fence_audit"
    out, = server.drain()                 # queued work rejected, not served
    assert out["outcome"] == "rejected" and out["breaker"] == "fence_audit"


# -- the loop end to end ------------------------------------------------------

def test_run_e2e_summary_and_stamps():
    from mfm_tpu.obs.instrument import serve_summary_from_registry

    before = serve_summary_from_registry()
    server = QueryServer(_engine(), ServePolicy(batch_max=3,
                                                default_deadline_s=60.0),
                         health="ok")
    buf = io.StringIO()
    lines = [_req(f"q{i}", benchmark="idx" if i == 0 else None)
             for i in range(7)]
    summary = server.run(iter(lines), buf, gulp=True)
    resps = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert len(resps) == 7 and all(r["outcome"] == "ok" for r in resps)
    assert all(r["health"] == "ok" and r["staleness"] == 0
               and r["degraded"] is False for r in resps)
    with_b = [r for r in resps if r["id"] == "q0"]
    assert "beta" in with_b[0] and "active_risk" in with_b[0]
    assert all("beta" not in r for r in resps if r["id"] != "q0")
    # registry is process-global: assert the DELTA this run contributed
    assert summary["requests_total"] - before["requests_total"] == 7
    assert summary["portfolios_total"] - before["portfolios_total"] == 7
    assert summary["breaker_state"] == "closed"
    assert summary["query_p50_latency_s"] is not None


# -- scenario-tagged serving --------------------------------------------------

def _scenario_table(engine):
    """Two stressed siblings of ``engine`` via the scenario engine's own
    serve-side sugar (exposures/benchmarks/dtype ride along)."""
    from mfm_tpu.scenario import ScenarioBuilder, ScenarioEngine

    sc = ScenarioEngine(np.asarray(engine._cov),
                        factor_names=engine.factor_names)
    results = sc.run([
        ScenarioBuilder("hot").vol_regime(2.0).build(),
        ScenarioBuilder("meltup").correlation(0.9).build(),
    ])
    return sc.query_engines(results, engine)


def test_every_response_carries_scenario_id():
    eng = _engine()
    server = QueryServer(eng, ServePolicy(default_deadline_s=60.0),
                         health="ok", scenarios=_scenario_table(eng))
    server.submit_line(_req("plain"))
    server.submit_line(_req("stressed", scenario="hot"))
    out = {r["id"]: r for r in server.drain()}
    assert out["plain"]["scenario_id"] is None
    assert out["stressed"]["scenario_id"] == "hot"
    assert out["plain"]["ok"] and out["stressed"]["ok"]
    # the stressed world answers with MORE risk, same portfolio
    assert out["stressed"]["total_vol"] > out["plain"]["total_vol"]


def test_scenario_groups_answer_from_their_own_engines():
    eng = _engine()
    table = _scenario_table(eng)
    server = QueryServer(eng, ServePolicy(batch_max=8,
                                          default_deadline_s=60.0),
                         health="ok", scenarios=table)
    for i in range(2):
        server.submit_line(_req(f"p{i}"))
        server.submit_line(_req(f"h{i}", scenario="hot"))
        server.submit_line(_req(f"m{i}", scenario="meltup"))
    out = {r["id"]: r for r in server.drain()}
    assert all(out[f"p{i}"]["scenario_id"] is None for i in range(2))
    assert all(out[f"h{i}"]["scenario_id"] == "hot" for i in range(2))
    assert all(out[f"m{i}"]["scenario_id"] == "meltup" for i in range(2))
    # each group's answer equals a dedicated server over that engine:
    # the plain group is the exact pre-scenario path
    for scen, rid in ((None, "p0"), ("hot", "h0"), ("meltup", "m0")):
        solo = QueryServer(eng if scen is None else table[scen],
                           ServePolicy(default_deadline_s=60.0), health="ok")
        solo.submit_line(_req("ref"))
        ref, = solo.drain()
        assert out[rid]["total_vol"] == ref["total_vol"], scen


def test_unknown_scenario_dead_letters_with_tag(tmp_path):
    dl = str(tmp_path / "dead.jsonl")
    eng = _engine()
    server = QueryServer(eng, ServePolicy(), health="ok",
                         dead_letter_path=dl, scenarios=_scenario_table(eng))
    resp, = server.submit_line(_req("bad", scenario="not-served"))
    assert resp["outcome"] == "dead_letter"
    assert resp["reasons"] == ["unknown_scenario"]
    assert resp["scenario_id"] == "not-served"
    # ANY tag is unknown when no table is served at all
    bare = QueryServer(_engine(), ServePolicy(), health="ok")
    resp, = bare.submit_line(_req("bad2", scenario="hot"))
    assert resp["reasons"] == ["unknown_scenario"]
    server.close()
    rec, = [json.loads(ln) for ln in open(dl)]
    assert rec["scenario_id"] == "not-served"


def test_scenario_swapped_out_between_admission_and_drain():
    eng = _engine()
    server = QueryServer(eng, ServePolicy(default_deadline_s=60.0),
                         health="ok", scenarios=_scenario_table(eng))
    server.submit_line(_req("r1", scenario="hot"))
    server.scenarios.pop("hot")           # table swap mid-flight
    resp, = server.drain()
    assert resp["outcome"] == "error" and not resp["ok"]
    assert resp["scenario_id"] == "hot"
    assert "no longer served" in resp["detail"]


# -- doctor --serve -----------------------------------------------------------

def _write_serve_manifest(d, serve_block):
    from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest

    man = build_run_manifest(backend="cpu",
                             health={"status": "ok", "checks": {}},
                             extra={"serve": serve_block})
    write_run_manifest(os.path.join(d, "serve_manifest.json"), man)


def _doctor_rc(args):
    from mfm_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["doctor", *args])
    return exc.value.code


def test_doctor_serve_audit(tmp_path, capsys):
    from mfm_tpu.data.artifacts import save_artifact

    d = str(tmp_path)
    # doctor refuses an empty dir outright; give it one healthy artifact
    save_artifact(os.path.join(d, "x.npz"), {"a": np.zeros(2)})
    # no serve manifest at all: --serve makes that a problem
    assert _doctor_rc([d, "--serve"]) == 1
    assert _doctor_rc([d]) == 0           # without --serve: nothing to audit
    # healthy summary: breaker closed, nothing shed
    _write_serve_manifest(d, {"breaker_state": "closed",
                              "breaker_open_total": 0, "shed_total": 0,
                              "shed_rate": 0.0, "requests_total": 5})
    capsys.readouterr()                   # drop the earlier runs' output
    assert _doctor_rc([d, "--serve"]) == 0
    rec = [r for r in json.loads(capsys.readouterr().out)["records"]
           if r["kind"] == "serve_manifest"][0]
    assert rec["status"] == "ok" and rec["breaker_state"] == "closed"
    # breaker open at shutdown: the serve run failed, exit nonzero
    _write_serve_manifest(d, {"breaker_state": "open",
                              "breaker_open_total": 2, "shed_total": 3,
                              "shed_rate": 0.1, "requests_total": 30})
    assert _doctor_rc([d, "--serve"]) == 1
    rec = [r for r in json.loads(capsys.readouterr().out)["records"]
           if r["kind"] == "serve_manifest"][0]
    assert rec["status"] == "unhealthy"
    assert any("OPEN at shutdown" in p for p in rec["problems"])
    assert any("shedding" in w for w in rec["warnings"])


def test_doctor_warns_when_serve_manifest_lacks_trace_id(tmp_path, capsys):
    from mfm_tpu.data.artifacts import save_artifact
    from mfm_tpu.obs.manifest import build_run_manifest, write_run_manifest

    d = str(tmp_path)
    save_artifact(os.path.join(d, "x.npz"), {"a": np.zeros(2)})
    block = {"breaker_state": "closed", "breaker_open_total": 0,
             "shed_total": 0, "shed_rate": 0.0, "requests_total": 5}
    # a pre-tracing manifest (no root trace_id): healthy, but warned —
    # the run cannot be joined to its trace
    _write_serve_manifest(d, block)
    assert _doctor_rc([d, "--serve"]) == 0
    rec = [r for r in json.loads(capsys.readouterr().out)["records"]
           if r["kind"] == "serve_manifest"][0]
    assert any("trace_id" in w for w in rec["warnings"])
    # with the root trace_id stamped the warning disappears
    write_run_manifest(
        os.path.join(d, "serve_manifest.json"),
        build_run_manifest(backend="cpu",
                           health={"status": "ok", "checks": {}},
                           extra={"serve": block, "trace_id": "a" * 32}))
    assert _doctor_rc([d, "--serve"]) == 0
    rec = [r for r in json.loads(capsys.readouterr().out)["records"]
           if r["kind"] == "serve_manifest"][0]
    assert not any("trace_id" in w for w in rec["warnings"])


# -- trace propagation --------------------------------------------------------

def test_supplied_trace_id_round_trips_and_spans_link():
    from mfm_tpu.obs import trace as _trace

    _trace.reset_tracing()
    try:
        server = QueryServer(_engine(), ServePolicy(default_deadline_s=60.0),
                             health="ok")
        tid = "t" * 32
        server.submit_line(_req("q1", trace_id=tid))
        resp, = server.drain()
        assert resp["trace_id"] == tid
        got = {s.name: s for s in _trace.spans()}
        req_sp, batch_sp = got["serve.request"], got["serve.batch"]
        assert req_sp.trace_id == tid and batch_sp.trace_id == tid
        assert batch_sp.parent_id == req_sp.span_id
        assert req_sp.attrs["request_id"] == "q1"
        assert req_sp.attrs["outcome"] == "ok"
        assert batch_sp.attrs["n"] == 1
    finally:
        _trace.reset_tracing()


def test_generated_trace_id_is_derived_from_request_bytes():
    from mfm_tpu.serve.server import _line_trace_id

    line = _req("q1")
    ids = []
    for _ in range(2):                    # two fresh servers, same bytes
        server = QueryServer(_engine(), ServePolicy(default_deadline_s=60.0),
                             health="ok")
        server.submit_line(line)
        resp, = server.drain()
        ids.append(resp["trace_id"])
    assert ids[0] == ids[1] == _line_trace_id(line)
    assert len(ids[0]) == 32


def test_dead_letter_and_reject_carry_trace_id(tmp_path):
    from mfm_tpu.serve.server import _line_trace_id

    dl = str(tmp_path / "dead.jsonl")
    server = QueryServer(_engine(), ServePolicy(), health="ok",
                         dead_letter_path=dl)
    resp, = server.submit_line(_req("bad", w=[1.0], trace_id="d" * 32))
    assert resp["trace_id"] == "d" * 32
    line2 = _req("bad2", w=[1.0])
    resp2, = server.submit_line(line2)
    assert resp2["trace_id"] == _line_trace_id(line2)
    server.close()
    recs = {r["id"]: r for r in map(json.loads, open(dl))}
    assert recs["bad"]["trace_id"] == "d" * 32
    assert recs["bad2"]["trace_id"] == _line_trace_id(line2)
    # breaker rejection (degraded health) stamps the id too
    deg = QueryServer(_engine(staleness=3), ServePolicy(), health="degraded")
    rej, = deg.submit_line(_req("r1", trace_id="e" * 32))
    assert rej["outcome"] == "rejected" and rej["trace_id"] == "e" * 32


def test_shed_and_deadline_outcomes_keep_trace_ids():
    from mfm_tpu.obs import trace as _trace

    _trace.reset_tracing()
    try:
        policy = ServePolicy(queue_max=2, batch_max=2,
                             default_deadline_s=60.0)
        server = QueryServer(_engine(), policy, health="ok")
        buf = io.StringIO()
        server.run(iter([_req(f"q{i}") for i in range(4)]), buf, gulp=True)
        resps = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert {r["outcome"] for r in resps} == {"shed", "ok"}
        assert all(len(r["trace_id"]) == 32 for r in resps)
        by_outcome = {}
        for s in _trace.spans():
            if s.name == "serve.request":
                by_outcome.setdefault(s.attrs.get("outcome"), []).append(s)
        assert len(by_outcome["shed"]) == 2
        assert len(by_outcome["ok"]) == 2
    finally:
        _trace.reset_tracing()
