"""Request-scoped tracing (mfm_tpu/obs/trace.py): span semantics, the
bounded ring, Chrome-trace export/validation, and crash atomicity.

The exporter tests mirror tests/test_obs.py's Prometheus discipline: the
trace we ship must round-trip through our own strict validator
(:func:`parse_chrome_trace`), because "Perfetto loads it" is the product
contract.  The SIGKILL drill carries ``chaos``/``slow`` like the manifest
one; the torn-file *detection* paths run in tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from mfm_tpu.obs.exporters import EVENT_REQUIRED_KEYS, route_events_to
from mfm_tpu.obs.instrument import TRACE_DROPPED_TOTAL, TRACE_SPANS_TOTAL
from mfm_tpu.obs.trace import (
    chrome_trace_events,
    clock_offset_from_probe,
    drain_spans,
    end_span,
    export_spans_to_events,
    current_trace_id,
    ingest_foreign_spans,
    parse_chrome_trace,
    render_chrome_trace,
    reset_tracing,
    set_ring_capacity,
    set_tracing,
    span,
    spans,
    start_span,
    write_chrome_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracing():
    reset_tracing()
    set_tracing(True)
    yield
    reset_tracing()
    set_tracing(True)


# -- span semantics -----------------------------------------------------------

def test_nested_spans_share_trace_and_link_parent():
    with span("outer", stage="risk") as outer:
        assert current_trace_id() == outer.trace_id
        with span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert current_trace_id() is None
    got = spans()                      # oldest first: inner closed first
    assert [s.name for s in got] == ["inner", "outer"]
    assert len(outer.trace_id) == 32 and len(outer.span_id) == 16
    assert all(s.dur_us >= 0.0 for s in got)
    assert outer.attrs == {"stage": "risk"}


def test_start_end_joins_the_open_trace():
    # the async half: a span started under a context-manager span joins its
    # trace (this is how a serve request parents its batch span)
    with span("request") as req:
        async_sp = start_span("batch")
        assert async_sp.trace_id == req.trace_id
        assert async_sp.parent_id == req.span_id
    end_span(async_sp, outcome="ok")   # ends AFTER the parent closed
    assert async_sp.attrs["outcome"] == "ok"
    # with no span open, a fresh trace begins, unparented
    lone = end_span(start_span("lone"))
    assert lone.parent_id is None and lone.trace_id != req.trace_id


def test_exception_ends_span_with_error_attr():
    with pytest.raises(RuntimeError, match="boom"):
        with span("doomed"):
            raise RuntimeError("boom")
    (sp,) = spans()
    assert sp.name == "doomed" and sp.attrs["error"].startswith(
        "RuntimeError: boom")


def test_disabled_tracing_records_nothing():
    before = TRACE_SPANS_TOTAL.value()
    set_tracing(False)
    with span("ghost"):
        pass
    assert spans() == [] and TRACE_SPANS_TOTAL.value() == before
    set_tracing(True)
    with span("real"):
        pass
    assert len(spans()) == 1


def test_ring_overflow_drops_oldest_and_counts():
    set_ring_capacity(8)
    dropped0 = TRACE_DROPPED_TOTAL.value()
    for i in range(20):
        end_span(start_span(f"s{i}"))
    got = spans()
    assert [s.name for s in got] == [f"s{i}" for i in range(12, 20)]
    assert TRACE_DROPPED_TOTAL.value() - dropped0 == 12
    with pytest.raises(ValueError, match="capacity"):
        set_ring_capacity(0)


def test_cross_thread_parenting_in_export():
    # a request admitted on one thread, batched on another: explicit ids
    # carry the trace across threads, and the export keeps tids distinct
    req = start_span("serve.request", request_id="q1")

    def worker():
        sp = start_span("serve.batch", trace_id=req.trace_id,
                        parent_id=req.span_id, n=1)
        time.sleep(0.001)
        end_span(sp, outcome="ok")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    end_span(req)
    events = parse_chrome_trace(render_chrome_trace())
    by_name = {e["name"]: e for e in events}
    batch, request = by_name["serve.batch"], by_name["serve.request"]
    assert batch["args"]["trace_id"] == request["args"]["trace_id"]
    assert batch["args"]["parent_id"] == request["args"]["span_id"]
    assert batch["tid"] != request["tid"]


# -- Chrome trace-event export ------------------------------------------------

def test_chrome_render_parses_and_carries_attrs():
    with span("run", cmd="risk", n=3):
        pass
    events = parse_chrome_trace(render_chrome_trace())
    (ev,) = events
    assert ev["ph"] == "X" and ev["cat"] == "mfm"
    assert ev["pid"] == os.getpid()
    assert ev["args"]["cmd"] == "risk" and ev["args"]["n"] == 3
    # the object wrapper is what Perfetto expects
    obj = json.loads(render_chrome_trace())
    assert set(obj) == {"traceEvents", "displayTimeUnit"}


@pytest.mark.parametrize("text,msg", [
    ('{"traceEvents": [', "torn trace file"),
    ('{"a": 1}', "traceEvents"),
    ('"just a string"', "object or array"),
    ('[42]', "not an object"),
    ('[{"name": "x", "ph": "Q", "ts": 0, "pid": 1, "tid": 1}]', "phase"),
    ('[{"ph": "X", "ts": 0, "dur": 1, "pid": 1, "tid": 1}]', "name"),
    ('[{"name": "x", "ph": "X", "ts": -5, "dur": 1, "pid": 1, "tid": 1}]',
     "ts"),
    ('[{"name": "x", "ph": "X", "ts": 0, "dur": 1, "pid": "p", "tid": 1}]',
     "pid"),
    ('[{"name": "x", "ph": "X", "ts": 0, "pid": 1, "tid": 1}]', "dur"),
    ('[{"name": "x", "ph": "B", "ts": 0, "pid": 1, "tid": 1, "args": []}]',
     "args"),
])
def test_parse_rejects_malformed(text, msg):
    with pytest.raises(ValueError, match=msg):
        parse_chrome_trace(text)


def test_parse_accepts_foreign_forms():
    # bare-array form and metadata ("M") events without timestamps both
    # load in Perfetto, so the validator must take them
    events = parse_chrome_trace(
        '[{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,'
        ' "args": {"name": "mfm"}},'
        ' {"name": "x", "ph": "X", "ts": 1.5, "dur": 0, "pid": 1, "tid": 0}]')
    assert len(events) == 2


def test_write_chrome_trace_is_atomic_and_loadable(tmp_path):
    with span("flush"):
        pass
    path = str(tmp_path / "metrics" / "trace.json")
    assert write_chrome_trace(path) == path
    assert not os.path.exists(path + ".tmp")
    with open(path, encoding="utf-8") as fh:
        (ev,) = parse_chrome_trace(fh.read())
    assert ev["name"] == "flush"


def test_export_spans_to_jsonl_events(tmp_path):
    log = str(tmp_path / "events.jsonl")
    with span("run", cmd="scenario"):
        pass
    route_events_to(log)
    try:
        assert export_spans_to_events() == 1
    finally:
        route_events_to(None)
    (line,) = open(log, encoding="utf-8").read().splitlines()
    ev = json.loads(line)
    for k in EVENT_REQUIRED_KEYS:
        assert k in ev
    assert ev["event"] == "span" and ev["name"] == "run"
    assert ev["attr_cmd"] == "scenario"
    assert len(ev["trace_id"]) == 32 and ev["dur_s"] >= 0.0


# -- fleet-wire span merge: clock-offset correction ---------------------------

def _worker_wire_span(name, start_us, dur_us=1000.0, trace_id="ab" * 16,
                      span_id="01" * 8, parent=None):
    return {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent, "start_us": float(start_us),
            "dur_us": float(dur_us), "wall_ts": 123.0, "tid": 7,
            "attrs": {}}


def test_drain_spans_is_destructive_and_json_safe():
    end_span(start_span("worker.batch", n=3))
    shipped = drain_spans()
    assert spans() == []               # shipped spans leave the worker ring
    assert len(shipped) == 1
    d = shipped[0]
    assert d["name"] == "worker.batch" and d["attrs"]["n"] == 3
    json.dumps(d)                      # the piggyback payload must be JSON


def test_clock_offset_probe_midpoint_and_uncertainty():
    # peer stamped its clock somewhere inside a 2 ms round trip centered
    # on local t=1.001 s; the peer runs 50 ms ahead
    off, unc = clock_offset_from_probe(1.000, 1.002, 1_051_000.0)
    assert off == pytest.approx(50_000.0)
    assert unc == pytest.approx(1_000.0)


@pytest.mark.parametrize("skew_ms", [50.0, -50.0])
def test_injected_skew_corrects_onto_local_timeline(skew_ms):
    """A worker clock +-50 ms off the frontend's: spans corrected by the
    probe-estimated offset land inside the dispatch window, in the true
    event order, stamped with the correction they received."""
    skew_us = skew_ms * 1e3
    # true (local-clock) worker activity: recv at 1.002 s, batch at 1.003 s,
    # inside the local dispatch window [1.000 s, 1.010 s]
    shipped = [
        _worker_wire_span("worker.recv", 1_002_000 + skew_us,
                          span_id="aa" * 8),
        _worker_wire_span("worker.batch", 1_003_000 + skew_us,
                          span_id="bb" * 8),
    ]
    # probe: peer stamped (midpoint + skew) inside a 2 ms RTT
    off, unc = clock_offset_from_probe(1.000, 1.002,
                                       1_001_000.0 + skew_us)
    assert off == pytest.approx(skew_us, abs=1.0)
    got = ingest_foreign_spans(shipped, offset_us=-off, uncertainty_us=unc,
                               window_us=(1_000_000.0, 1_010_000.0),
                               worker=3)
    assert [s.name for s in got] == ["worker.recv", "worker.batch"]
    assert got[0].start_us == pytest.approx(1_002_000.0, abs=unc)
    assert got[1].start_us == pytest.approx(1_003_000.0, abs=unc)
    assert got[0].start_us < got[1].start_us   # true order survives
    for s in got:
        assert s.attrs["clock_offset_us"] == pytest.approx(-off)
        assert s.attrs["clock_uncertainty_us"] == pytest.approx(unc)
        assert s.attrs["worker"] == 3
        assert "clock_skew" not in s.attrs
    # the merged ring holds them for the Chrome export
    assert [s.span_id for s in spans()] == ["aa" * 8, "bb" * 8]


def test_uncorrectable_skew_flagged_never_reordered_or_clamped():
    """No usable offset estimate: a span whose corrected extent falls
    outside the dispatch window beyond the uncertainty is FLAGGED — its
    timestamps are neither clamped into the window nor reordered."""
    from mfm_tpu.obs.instrument import TRACE_SKEW_UNCORRECTABLE_TOTAL
    before = TRACE_SKEW_UNCORRECTABLE_TOTAL.value()
    shipped = [_worker_wire_span("worker.batch", 1_052_000.0,
                                 span_id="cc" * 8),
               _worker_wire_span("worker.recv", 1_051_000.0,
                                 span_id="dd" * 8)]
    got = ingest_foreign_spans(shipped, offset_us=0.0, uncertainty_us=500.0,
                               window_us=(1_000_000.0, 1_010_000.0),
                               worker=1)
    assert [s.attrs.get("clock_skew") for s in got] == \
        ["uncorrectable", "uncorrectable"]
    # not clamped: the raw (offset-applied) timestamps survive
    assert got[0].start_us == 1_052_000.0
    assert got[1].start_us == 1_051_000.0
    # not reordered: ring order is ship order, even though start_us isn't
    assert [s.span_id for s in spans()] == ["cc" * 8, "dd" * 8]
    assert TRACE_SKEW_UNCORRECTABLE_TOTAL.value() == before + 2


def test_merged_spans_render_one_timeline_per_trace():
    tid = "fe" * 16
    sp = start_span("fleet.dispatch", trace_id=tid, replica=0)
    end_span(sp)
    ingest_foreign_spans(
        [_worker_wire_span("worker.batch", 2_000.0, trace_id=tid,
                           parent=sp.span_id)],
        offset_us=0.0, uncertainty_us=10.0, worker=0)
    events = parse_chrome_trace(render_chrome_trace())
    by_name = {e["name"]: e for e in events}
    assert by_name["fleet.dispatch"]["args"]["trace_id"] == tid
    assert by_name["worker.batch"]["args"]["trace_id"] == tid
    assert by_name["worker.batch"]["args"]["parent_id"] == sp.span_id
    assert by_name["worker.batch"]["args"]["clock_offset_us"] == 0.0


def test_ingest_disabled_tracing_is_a_noop():
    set_tracing(False)
    got = ingest_foreign_spans([_worker_wire_span("worker.batch", 1.0)],
                               offset_us=0.0)
    assert got == [] and spans() == []


# -- crash atomicity ----------------------------------------------------------

_FLUSH_SCRIPT = """\
import sys
sys.path.insert(0, {repo!r})
from mfm_tpu.obs.trace import end_span, start_span, write_chrome_trace
end_span(start_span("cli.risk"))
end_span(start_span("serve.request"))
write_chrome_trace({path!r})
"""


def _flush_in_subprocess(path, kill=False):
    env = dict(os.environ)
    env.pop("MFM_CHAOS_KILL", None)
    if kill:
        env["MFM_CHAOS_KILL"] = "trace.after_tmp"
    return subprocess.run(
        [sys.executable, "-c",
         _FLUSH_SCRIPT.format(repo=REPO, path=path)],
        env=env, capture_output=True, text=True, timeout=120)


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_mid_trace_flush_leaves_no_torn_file(tmp_path):
    path = str(tmp_path / "trace.json")
    proc = _flush_in_subprocess(path, kill=True)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # the crash fell between tmp write and rename: no half-written
    # trace.json may exist for a reader to choke on
    assert not os.path.exists(path)
    # the retried flush wins cleanly and the result passes the validator
    assert _flush_in_subprocess(path).returncode == 0
    with open(path, encoding="utf-8") as fh:
        events = parse_chrome_trace(fh.read())
    assert [e["name"] for e in events] == ["cli.risk", "serve.request"]


# -- the compile and overhead contracts ---------------------------------------

def test_traced_steady_state_adds_no_compiles():
    """Spans bracket the jit boundary from the host side; a traced steady
    state must stay compile-free (the serving-loop contract rides on it)."""
    import jax
    import jax.numpy as jnp

    from mfm_tpu.utils.contracts import assert_max_compiles

    @jax.jit
    def step(x):
        return jnp.sum(x * 2.0)

    with span("warmup"):
        float(step(jnp.ones(16)))
    with assert_max_compiles(0, what="traced steady state"):
        for i in range(5):
            with span("update", i=i):
                float(step(jnp.ones(16)))


def test_span_open_close_is_cheap():
    """The per-request cost the bench reports as tracing_overhead_frac:
    one span open/close.  1 ms is ~100x the observed cost — generous
    enough for a loaded CI box, tight enough to catch an accidental
    flush-per-span."""
    for _ in range(50):                # warm allocator paths
        end_span(start_span("warm"))
    n = 200
    t0 = time.perf_counter()
    for i in range(n):
        with span("probe", i=i):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 1e-3, f"span open/close took {per_span:.6f}s"
