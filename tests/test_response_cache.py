"""Response cache (mfm_tpu/serve/cache.py): hits byte-identical to cold
computation modulo the identity keys, uncacheable-outcome exclusion, the
LRU entry/byte bounds + eviction accounting under a thread hammer, the
generation/scenario fence-in-key invalidation, the hit-path reload poll
(an all-hits stream must still move the fence), per-replica coherence
through the fleet front end, and construct warm-start parity vs the cold
solve."""

import io
import json
import threading

import numpy as np
import pytest

from mfm_tpu.serve import (
    CacheFill,
    Coalescer,
    FleetServer,
    QueryEngine,
    QueryServer,
    ReplicaDeadError,
    ResponseCache,
    ServePolicy,
    WarmStartIndex,
    cacheable_response,
)

K = 4


def _engine(scale=1.0):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((K, K)) / 2
    cov = (a @ a.T + 1e-3 * np.eye(K)) * 1e-4 * scale
    return QueryEngine(cov, factor_names=["country", "ind0", "size", "mom"],
                       benchmarks={"idx": rng.standard_normal(K)})


def _server(batch_max=64, health="ok", **kw):
    policy = ServePolicy(batch_max=batch_max, queue_max=4096,
                        default_deadline_s=600.0)
    return QueryServer(_engine(), policy, health=health,
                       scenarios={"stress": _engine(scale=1.44)}, **kw)


def _line(i, body_seed, **extra):
    rng = np.random.default_rng(body_seed)
    req = {"id": f"c{i}",
           "weights": np.round(0.2 * rng.standard_normal(K), 6).tolist(),
           "deadline_s": 600.0, **extra}
    return json.dumps(req, sort_keys=True)


def _strip(resp: dict) -> str:
    return json.dumps({k: v for k, v in resp.items()
                       if k not in ("id", "trace_id")}, sort_keys=True)


def _ok_resp(i):
    return {"id": f"r{i}", "ok": True, "outcome": "ok", "degraded": False,
            "trace_id": f"t{i}", "total_vol": float(i)}


# -- key derivation and cacheability ------------------------------------------

def test_key_excludes_identity_keys():
    cache = ResponseCache(8, 1 << 20)
    k1, rid1, _ = cache.key_for(_line(0, body_seed=7))
    k2, rid2, _ = cache.key_for(_line(1, body_seed=7))
    assert k1 == k2 and (rid1, rid2) == ("c0", "c1")
    k3, _, _ = cache.key_for(_line(2, body_seed=8))
    assert k3 != k1


def test_key_caller_trace_id_round_trips():
    cache = ResponseCache(8, 1 << 20)
    _, _, tid = cache.key_for(json.dumps(
        {"id": "a", "trace_id": "mine", "weights": [0.1] * K}))
    assert tid == "mine"
    # no caller trace id -> the deterministic line hash the cold path stamps
    from mfm_tpu.serve.server import _line_trace_id
    line = json.dumps({"id": "a", "weights": [0.1] * K})
    _, _, tid2 = cache.key_for(line)
    assert tid2 == _line_trace_id(line)


def test_unparseable_lines_uncacheable():
    cache = ResponseCache(8, 1 << 20)
    for bad in ('{"id": "x", "weights": [0.1,', '[1, 2, 3]', '"str"'):
        assert cache.key_for(bad) is None
        assert cache.lookup(bad) == (None, None)
    assert cache.stats()["misses"] == 0   # uncacheable is not a miss


def test_cacheable_response_predicate():
    assert cacheable_response(_ok_resp(0))
    assert not cacheable_response(dict(_ok_resp(0), degraded=True))
    assert not cacheable_response(dict(_ok_resp(0), ok=False))
    assert not cacheable_response(dict(_ok_resp(0), outcome="rejected"))
    assert not cacheable_response(dict(_ok_resp(0), outcome="dead_letter"))
    assert not cacheable_response(None)


# -- hit == cold, byte for byte -----------------------------------------------

@pytest.mark.parametrize("extra", [{}, {"benchmark": "idx"},
                                   {"scenario": "stress"},
                                   {"construct": {"solver": "min_vol"}}])
def test_hit_bitwise_equal_to_cold_modulo_identity(extra):
    """A hit re-stamped with the second caller's id/trace id must encode
    byte-identically to what a cold server would compute for that exact
    line — across every request type."""
    cache = ResponseCache(64, 1 << 20)
    co = Coalescer(_server(batch_max=8), linger_s=100.0, cache=cache)
    first = _line(0, body_seed=5, **extra)
    second = _line(1, body_seed=5, **extra)   # same body, different caller
    cold_pairs = co.submit(first) + co.flush()
    assert len(cold_pairs) == 1 and cold_pairs[0][1]["outcome"] == "ok"
    hit_pairs = co.submit(second)             # answered without a drain
    assert len(hit_pairs) == 1
    assert cache.stats() == dict(cache.stats(), hits=1, misses=1)

    out = io.StringIO()
    _server(batch_max=8).run([second], out, gulp=True)
    want = out.getvalue().splitlines()[0]
    assert json.dumps(hit_pairs[0][1], sort_keys=True) == want


def test_uncacheable_outcomes_never_stored():
    # degraded stamps (health != ok) must not freeze into cached answers
    cache = ResponseCache(64, 1 << 20)
    co = Coalescer(_server(batch_max=8, health="unknown"), linger_s=100.0,
                   cache=cache)
    line = _line(0, body_seed=5)
    pairs = co.submit(line) + co.flush()
    assert pairs[0][1]["degraded"] is True
    assert len(cache) == 0
    again = co.submit(_line(1, body_seed=5)) + co.flush()
    assert again[0][1]["outcome"] == "ok"     # still served, just not cached
    assert cache.stats()["hits"] == 0 and cache.stats()["misses"] == 2

    # dead-letter acks ride a CacheFill origin too and must be refused
    cache2 = ResponseCache(64, 1 << 20)
    co2 = Coalescer(_server(batch_max=8), linger_s=100.0, cache=cache2)
    bad = json.dumps({"id": "bad", "weights": [float("nan")] * K})
    acks = co2.submit(bad)
    assert acks and acks[0][1]["outcome"] != "ok"
    assert len(cache2) == 0


def test_absorb_unwraps_cachefill_and_populates():
    cache = ResponseCache(8, 1 << 20)
    key, _, _ = cache.key_for(_line(0, body_seed=1))
    pairs = cache.absorb([(CacheFill("conn7", key), _ok_resp(0)),
                          ("conn8", _ok_resp(1))])
    assert [o for o, _ in pairs] == ["conn7", "conn8"]   # wrapper never leaks
    assert len(cache) == 1
    resp, token = cache.lookup(_line(9, body_seed=1))
    assert resp is not None and resp["id"] == "c9"
    assert _strip(resp) == _strip(_ok_resp(0))


# -- bounds and eviction ------------------------------------------------------

def test_lru_entry_bound_and_recency():
    cache = ResponseCache(4, 1 << 20)
    lines = [_line(i, body_seed=100 + i) for i in range(6)]
    for i, ln in enumerate(lines):
        key, _, _ = cache.key_for(ln)
        assert cache.put(key, _ok_resp(i))
    assert len(cache) == 4 and cache.stats()["evictions"] == 2
    assert cache.lookup(lines[0])[0] is None   # oldest two evicted
    assert cache.lookup(lines[1])[0] is None
    assert cache.lookup(lines[2])[0] is not None
    # a hit refreshes recency: line 3 is touched, so inserting one more
    # evicts line 4, not line 3
    assert cache.lookup(lines[3])[0] is not None
    key, _, _ = cache.key_for(_line(9, body_seed=900))
    cache.put(key, _ok_resp(9))
    assert cache.lookup(lines[3])[0] is not None
    assert cache.lookup(lines[4])[0] is None


def test_byte_bound_evicts_and_accounts():
    one = len(json.dumps({k: v for k, v in _ok_resp(0).items()
                          if k not in ("id", "trace_id")}, sort_keys=True))
    cache = ResponseCache(100, max_bytes=2 * one + 1)
    for i in range(5):
        key, _, _ = cache.key_for(_line(i, body_seed=200 + i))
        cache.put(key, _ok_resp(i))
    assert len(cache) == 2 and cache.resident_bytes <= 2 * one + 1
    assert cache.stats()["evictions"] == 3
    # a body larger than the whole budget cannot become resident
    tiny = ResponseCache(100, max_bytes=one - 1)
    key, _, _ = tiny.key_for(_line(0, body_seed=0))
    tiny.put(key, _ok_resp(0))
    assert len(tiny) == 0 and tiny.resident_bytes == 0


def test_thread_hammer_bounds_hold():
    """8 threads hammer lookup/put over more distinct bodies than the
    cache can hold: no exceptions, both bounds hold, the hit/miss tally
    balances, and the resident-byte count matches the entries exactly."""
    cache = ResponseCache(16, 8 << 10)
    lines = [_line(i, body_seed=300 + i) for i in range(48)]
    per_thread = 200
    errors = []

    def hammer(tid):
        rng = np.random.default_rng(tid)
        try:
            for n in range(per_thread):
                ln = lines[int(rng.integers(len(lines)))]
                resp, token = cache.lookup(ln)
                if resp is None and token is not None:
                    cache.put(token, _ok_resp(n))
        except Exception as e:   # noqa: BLE001 — surfaced via errors
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    s = cache.stats()
    assert s["hits"] + s["misses"] == 8 * per_thread
    assert s["entries"] <= 16 and s["resident_bytes"] <= 8 << 10
    assert s["resident_bytes"] == sum(len(body) for body, _
                                      in cache._entries.values())


# -- fences -------------------------------------------------------------------

def test_generation_fence_in_key():
    cache = ResponseCache(8, 1 << 20, generation=3)
    line = _line(0, body_seed=5)
    key, _, _ = cache.key_for(line)
    cache.put(key, _ok_resp(0))
    assert cache.lookup(line)[0] is not None
    cache.set_fence(generation=4)
    assert cache.lookup(line)[0] is None       # invalidated without a sweep
    key4, _, _ = cache.key_for(line)
    cache.put(key4, _ok_resp(4))
    assert len(cache) == 2                     # both generations resident
    cache.set_fence(generation=3)
    resp, _ = cache.lookup(line)
    assert resp is not None and _strip(resp) == _strip(_ok_resp(0))


def test_scenario_fence_invalidates_exactly_that_scenario():
    cache = ResponseCache(8, 1 << 20, scenario_hashes={"stress": "h1"})
    tagged = _line(0, body_seed=5, scenario="stress")
    plain = _line(1, body_seed=5)
    unknown = _line(2, body_seed=5, scenario="other")
    for i, ln in enumerate((tagged, plain, unknown)):
        key, _, _ = cache.key_for(ln)
        cache.put(key, _ok_resp(i))
    cache.set_fence(scenario_hashes={"stress": "h2"})
    assert cache.lookup(tagged)[0] is None     # spec hash moved
    assert cache.lookup(plain)[0] is not None  # untagged untouched
    # names absent from the map fence on the name itself
    assert cache.lookup(unknown)[0] is not None


def test_hit_path_reload_poll_moves_fence():
    """A pure repeat stream is all hits and never drains — the throttled
    hit-path poll is the only thing that can run the reload.  Without it
    the stream would answer from a retired generation forever."""
    gen_b = _engine(scale=2.25)
    cache = ResponseCache(8, 1 << 20, generation=0)
    flips = {"armed": False}

    def reload_fn():
        if not flips["armed"]:
            return None
        flips["armed"] = False
        cache.set_fence(generation=1)
        return {"engine": gen_b, "health": "ok"}

    t = {"now": 0.0}
    server = QueryServer(_engine(), ServePolicy(batch_max=8,
                                                default_deadline_s=600.0),
                         health="ok", reload_fn=reload_fn)
    co = Coalescer(server, linger_s=1.0, clock=lambda: t["now"], cache=cache)
    pre = [(co.submit(_line(i, body_seed=5)) + co.flush())[0][1]
           for i in range(4)]
    assert cache.stats()["hits"] == 3
    flips["armed"] = True
    t["now"] = 5.0                    # past the linger budget: next submit polls
    post = [(co.submit(_line(10 + i, body_seed=5)) + co.flush())[0][1]
            for i in range(4)]
    stale = {_strip(r) for r in pre}
    assert all(_strip(r) not in stale for r in post)
    assert post[0]["total_vol"] != pre[0]["total_vol"]
    assert {_strip(r) for r in post[1:]} == {_strip(post[0])}  # re-warmed


# -- coalescer / fleet coherence ----------------------------------------------

def test_coalesced_cache_bitwise_vs_sequential():
    """Mixed request types, each body submitted twice: the second round is
    all hits, and every response — hit or cold — is byte-identical per id
    to the plain sequential no-cache loop."""
    kinds = [{}, {"benchmark": "idx"}, {"scenario": "stress"},
             {"construct": {"solver": "min_vol"}},
             {"construct": {"solver": "risk_parity"}}]
    round1 = [_line(i, body_seed=400 + i, **kinds[i % 5]) for i in range(10)]
    round2 = [_line(100 + i, body_seed=400 + i, **kinds[i % 5])
              for i in range(10)]
    cache = ResponseCache(64, 1 << 20)
    co = Coalescer(_server(batch_max=16), linger_s=100.0, cache=cache)
    got = {}
    for ln in round1:
        for _, r in co.submit(ln) + co.flush():
            got[r["id"]] = r
    for ln in round2:
        for _, r in co.submit(ln) + co.flush():
            got[r["id"]] = r
    assert cache.stats()["hits"] == 10

    out = io.StringIO()
    _server(batch_max=16).run(round1 + round2, out, gulp=True)
    ref = {json.loads(ln)["id"]: ln for ln in out.getvalue().splitlines()}
    assert set(got) == set(ref)
    for rid, r in got.items():
        assert json.dumps(r, sort_keys=True) == ref[rid]


class _StubProc:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc


class _StubReplica:
    """Duck-typed replica answering through a real in-process server, so
    fleet responses stay bitwise-comparable to the sequential loop."""

    def __init__(self, idx):
        self.idx = idx
        self.quarantined = False
        self.delivered = {}
        self.proc = _StubProc()
        self._wserver = _server(batch_max=64)

    @property
    def alive(self):
        return not self.quarantined and self.proc.poll() is None

    def run_batch(self, lines):
        resps = {}
        for i, ln in enumerate(lines):
            for o, r in self._wserver.submit_line_routed(ln, origin=i):
                resps[o] = r
        while self._wserver._queue:
            for o, r in self._wserver.drain_routed():
                resps[o] = r
        return resps

    def close(self, timeout=None):
        if self.proc.rc is None:
            self.proc.rc = 0
        return self.proc.rc


def test_fleet_cache_coherent_across_replicas():
    """The cache sits in the front end, so which replica computed a miss
    is invisible: a repeat round over a 2-replica fleet is all hits and
    every response matches the single-process no-cache loop per id."""
    cache = ResponseCache(64, 1 << 20)
    fleet = FleetServer(_server(batch_max=4),
                        [_StubReplica(0), _StubReplica(1)],
                        linger_s=10.0, cache=cache)
    round1 = [_line(i, body_seed=500 + i) for i in range(8)]
    round2 = [_line(100 + i, body_seed=500 + i) for i in range(8)]
    got = {}
    for i, ln in enumerate(round1):
        for _, r in fleet.submit(ln, origin=i):
            got[r["id"]] = r
    for _, r in fleet.flush():
        got[r["id"]] = r
    for i, ln in enumerate(round2):
        for _, r in fleet.submit(ln, origin=100 + i):
            got[r["id"]] = r
    for _, r in fleet.stop():
        got[r["id"]] = r
    fleet.close_replicas()
    assert cache.stats()["hits"] == 8

    out = io.StringIO()
    _server(batch_max=4).run(round1 + round2, out, gulp=True)
    ref = {json.loads(ln)["id"]: ln for ln in out.getvalue().splitlines()}
    assert set(got) == set(ref)
    for rid, r in got.items():
        assert json.dumps(r, sort_keys=True) == ref[rid]


# -- warm-start tier ----------------------------------------------------------

def test_warm_index_nearest_tolerance():
    idx = WarmStartIndex(tol=0.05, per_solver=4)
    base = np.full(K, 0.5)
    solved = np.full(K, 0.25)
    idx.add("min_vol", 0.0, base, solved)
    near = base + 0.01
    got = idx.nearest("min_vol", 0.0, near)
    assert got is not None and np.array_equal(got, solved)
    got[:] = -1.0                                  # callers get a copy
    assert np.array_equal(idx.nearest("min_vol", 0.0, near), solved)
    assert idx.nearest("min_vol", 0.0, base + 10.0) is None   # outside tol
    assert idx.nearest("risk_parity", 0.0, near) is None      # other solver
    assert idx.nearest("min_vol", 0.5, near) is None          # other hmax


def test_warm_start_parity_vs_cold():
    """A near-miss construct solve seeds from the cached solution at the
    reduced step budget, records the parity contract on the response, and
    converges to the cold optimum within tolerance; a far book stays cold
    and byte-identical to the no-index server."""
    from mfm_tpu.grad.engine import MINVOL_STEPS

    warm_idx = WarmStartIndex(tol=0.05)
    ws = _server(batch_max=8, warm_index=warm_idx)
    cs = _server(batch_max=8)
    rng = np.random.default_rng(4242)
    base = np.round(0.2 * rng.standard_normal(K), 6)

    def solve(server, book, rid):
        server.submit_line(json.dumps(
            {"id": rid, "weights": book.tolist(), "deadline_s": 600.0,
             "construct": {"solver": "min_vol"}}, sort_keys=True))
        (resp,) = server.drain()
        assert resp["outcome"] == "ok"
        return resp

    seed = solve(ws, base, "seed")
    assert "warm_start" not in seed                # cold solves unmarked

    near = np.round(base + 0.002 * rng.standard_normal(K), 6)
    warm = solve(ws, near, "warm")
    cold = solve(cs, near, "cold")
    steps = max(1, MINVOL_STEPS // WarmStartIndex.STEPS_DIVISOR)
    assert warm["warm_start"] == {"used": True, "steps": steps,
                                  "steps_saved": MINVOL_STEPS - steps,
                                  "parity": "seeded"}
    assert "warm_start" not in cold
    assert abs(warm["total_vol"] - cold["total_vol"]) <= 1e-5
    assert np.max(np.abs(np.array(warm["weights"])
                         - np.array(cold["weights"]))) <= 0.01
    assert warm_idx.stats()["uses"] == 1
    assert warm_idx.stats()["steps_saved"] == MINVOL_STEPS - steps

    far = np.round(base + np.linspace(1.0, 2.0, K), 6)
    far_ws = solve(ws, far, "far")
    far_cs = solve(cs, far, "far")
    assert "warm_start" not in far_ws
    assert json.dumps(far_ws, sort_keys=True) == \
        json.dumps(far_cs, sort_keys=True)
