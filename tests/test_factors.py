"""End-to-end FactorEngine parity vs a long-frame pandas golden pipeline.

The golden path rebuilds the reference's master-frame semantics: one row per
(stock, traded day), per-stock rolling over the stock's own rows, per-date
cross-sections — then results are compared at observed (date, stock) cells.
"""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from mfm_tpu.config import FactorConfig, RollingSpec
from mfm_tpu.data.synthetic import synthetic_market_panel
from mfm_tpu.factors.engine import FactorEngine

import golden

CFG = FactorConfig(
    beta=RollingSpec(window=40, half_life=10, min_periods=8),
    rstr_total=60, rstr_lag=5, rstr_half_life=15, rstr_min_periods=8,
    dastd=RollingSpec(window=40, half_life=8, min_periods=8),
    cmra_window=30,
    stom=RollingSpec(window=10, min_periods=7),
    stoq=RollingSpec(window=21, min_periods=14),
    stoa=RollingSpec(window=42, min_periods=21),
)


@pytest.fixture(scope="module")
def setup():
    data = synthetic_market_panel(T=130, N=25, n_industries=5, seed=3,
                                  missing=0.03, listing_gap=0.3)
    from mfm_tpu.data.synthetic import panel_to_engine_fields

    # default float dtype (f64 under the test conftest's x64 switch)
    fields = panel_to_engine_fields(data, jnp.asarray(0.0).dtype)
    eng = FactorEngine(fields, jnp.asarray(data["index_close"]), config=CFG)
    out = {k: np.asarray(v) for k, v in eng.run(post_process=False).items()}
    return data, out


def _stock_frames(data):
    """Per-stock long series over that stock's observed days only."""
    obs = data["observed"]
    mkt = pd.Series(data["index_close"]).pct_change().to_numpy()
    frames = {}
    for n in range(obs.shape[1]):
        sel = obs[:, n]
        close = pd.Series(data["close"][sel, n])
        frames[n] = dict(
            t_index=np.nonzero(sel)[0],
            ret=close.pct_change(),
            log_ret=np.log(close) - np.log(close.shift(1)),
            market=pd.Series(mkt[sel]),
            turnover=pd.Series(data["turnover_rate"][sel, n]),
        )
    return frames


def test_returns_match_per_stock_pct_change(setup):
    data, out = setup
    for n, f in _stock_frames(data).items():
        got = out["ret"][f["t_index"], n]
        np.testing.assert_allclose(got, f["ret"].to_numpy(), rtol=1e-10,
                                   atol=1e-14, equal_nan=True)


def test_beta_hsigma_end_to_end(setup):
    data, out = setup
    for n, f in _stock_frames(data).items():
        gb, gh = golden.golden_beta_hsigma(
            f["ret"], f["market"],
            T=CFG.beta.window, hl=CFG.beta.half_life, minp=CFG.beta.min_periods,
        )
        np.testing.assert_allclose(out["BETA"][f["t_index"], n], gb,
                                   rtol=1e-6, atol=1e-9, equal_nan=True)
        np.testing.assert_allclose(out["HSIGMA"][f["t_index"], n], gh,
                                   rtol=1e-6, atol=1e-9, equal_nan=True)


def test_rstr_dastd_cmra_end_to_end(setup):
    data, out = setup
    for n, f in _stock_frames(data).items():
        g_rstr = golden.golden_rstr(f["log_ret"], T=CFG.rstr_total, L=CFG.rstr_lag,
                                    hl=CFG.rstr_half_life, minp=CFG.rstr_min_periods)
        np.testing.assert_allclose(out["RSTR"][f["t_index"], n], g_rstr,
                                   rtol=1e-7, atol=1e-11, equal_nan=True)
        g_dastd = golden.golden_dastd(f["ret"] - f["market"], T=CFG.dastd.window,
                                      hl=CFG.dastd.half_life,
                                      minp=CFG.dastd.min_periods)
        np.testing.assert_allclose(out["DASTD"][f["t_index"], n], g_dastd,
                                   rtol=1e-7, atol=1e-11, equal_nan=True)
        g_cmra = golden.golden_cmra(f["log_ret"], T=CFG.cmra_window)
        np.testing.assert_allclose(out["CMRA"][f["t_index"], n], g_cmra,
                                   rtol=1e-7, atol=1e-11, equal_nan=True)


def test_liquidity_end_to_end(setup):
    data, out = setup
    for n, f in _stock_frames(data).items():
        dtv = f["turnover"] / 100.0
        for name, (w, mp) in {
            "STOM": (CFG.stom.window, CFG.stom.min_periods),
            "STOQ": (CFG.stoq.window, CFG.stoq.min_periods),
            "STOA": (CFG.stoa.window, CFG.stoa.min_periods),
        }.items():
            base = dtv.rolling(w, min_periods=mp).sum()
            g = np.log(base.replace(0, np.nan)).to_numpy()
            np.testing.assert_allclose(out[name][f["t_index"], n], g,
                                       rtol=1e-9, atol=1e-12, equal_nan=True)


def test_elementwise_factors(setup):
    data, out = setup
    obs = data["observed"]
    np.testing.assert_allclose(
        out["SIZE"][obs], np.log(data["total_mv"][obs]), rtol=1e-12
    )
    pb = data["pb"][obs]
    bp = out["BP"][obs]
    np.testing.assert_allclose(bp[pb > 0], 1 / pb[pb > 0], rtol=1e-12)
    assert np.all(np.isnan(bp[~(pb > 0)]))
    np.testing.assert_allclose(
        out["YOYProfit"][obs], data["q_profit_yoy"][obs] / 100, rtol=1e-12
    )
    book = data["total_hldr_eqy_inc_min_int"][obs]
    blev = out["BLEV"][obs]
    expect = (book + data["total_ncl"][obs]) / book
    np.testing.assert_allclose(blev[book > 0], expect[book > 0], rtol=1e-12)
    assert np.all(np.isnan(blev[~(book > 0)]))


def test_nlsize_matches_per_date_regression(setup):
    data, out = setup
    obs = data["observed"]
    size = np.where(obs, np.log(data["total_mv"]), np.nan)
    ti, si = np.nonzero(obs)
    df = pd.DataFrame({"trade_date": ti, "SIZE": size[ti, si]})
    g = golden.golden_nlsize(df)
    np.testing.assert_allclose(out["NLSIZE"][ti, si], g, rtol=1e-7, atol=1e-10,
                               equal_nan=True)


def test_cetop_ttm_semantics(setup):
    data, out = setup
    obs = data["observed"]
    # golden TTM: unique (stock, report) pairs in order, rolling-4 sum
    T, N = obs.shape
    for n in range(N):
        sel = obs[:, n]
        rid = data["end_date_code"][sel, n]
        cash = data["n_cashflow_act"][sel, n]
        rep = pd.DataFrame({"rid": rid, "v": cash}).drop_duplicates("rid")
        rep["ttm"] = rep["v"].rolling(4, min_periods=4).sum()
        ttm_by_rid = dict(zip(rep["rid"], rep["ttm"]))
        mv = data["total_mv"][sel, n]
        expect_ttm = np.array([ttm_by_rid.get(r, np.nan) for r in rid])
        expect = np.where((mv > 0) & (expect_ttm > 0), expect_ttm / mv, np.nan)
        np.testing.assert_allclose(out["CETOP"][np.nonzero(sel)[0], n], expect,
                                   rtol=1e-9, atol=1e-12, equal_nan=True)


def test_nlsize_caller_mask_with_nan_size_drops_row_only():
    """A caller `valid` mask that marks a NaN size as valid must drop that
    row (the raw form's internal isfinite behavior), not NaN-poison the
    whole date through the centered-basis mean."""
    import jax.numpy as jnp

    from mfm_tpu.factors.style import compute_nlsize

    rng = np.random.default_rng(0)
    size = rng.normal(10.0, 1.0, (3, 8))
    size[1, 2] = np.nan
    sloppy_valid = jnp.ones((3, 8), bool)  # claims everything is valid

    out = np.asarray(compute_nlsize(jnp.asarray(size), sloppy_valid))
    clean = np.asarray(compute_nlsize(jnp.asarray(size)))  # derived mask
    np.testing.assert_allclose(out, clean, rtol=1e-10, equal_nan=True)
    assert np.isnan(out[1, 2])
    assert np.isfinite(out[1, [0, 1, 3, 4, 5, 6, 7]]).all()
