"""bench.py's roofline accounting: the analytic FLOP/byte models and the
peak-fraction arithmetic must stay self-consistent (they are the r5
"achieved-vs-peak" evidence fields in every driver BENCH record)."""

import importlib.util
import os

import pytest


@pytest.fixture(scope="module")
def bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_stage_models_positive_and_eigen_dominant(bench):
    m = bench._riskmodel_stage_models(1390, 300, 31, 10, 42, 100, sweeps=4)
    assert set(m) == {"regression", "newey_west", "eigen", "vol_regime"}
    for name, rec in m.items():
        assert rec["gflop"] > 0 and rec["gbyte"] > 0, name
    # the eigen MC is the workload's FLOP center of mass by orders of
    # magnitude — if a model edit breaks that, the roofline story is wrong
    assert m["eigen"]["gflop"] > 50 * m["regression"]["gflop"]


def test_roofline_fractions_on_known_chip(bench, monkeypatch):
    class Dev:
        platform = "tpu"
        device_kind = "TPU v5e"

    import jax

    monkeypatch.setattr(jax, "devices", lambda *a: [Dev()])
    models = bench._riskmodel_stage_models(1390, 300, 31, 10, 42, 100, 4)
    out = bench._roofline({"regression": 0.05, "newey_west": 0.07,
                           "eigen": 0.68, "vol_regime": 0.07}, models)
    assert out["device_kind"] == "TPU v5e"
    assert out["peaks"]["mxu_bf16_tflops"] == 197.0
    # mxu-bound stage: fraction = gflops / (mxu peak)
    # fractions are rounded to 4 decimals in the record — compare at that
    # granularity
    reg = out["regression"]
    assert reg["frac_of_peak"] == pytest.approx(
        reg["achieved_gflops"] / 197e3, abs=5.1e-5)
    # vpu-bound stage: held to the 1/25 estimate
    eig = out["eigen"]
    assert eig["frac_of_peak"] == pytest.approx(
        eig["achieved_gflops"] / (197e3 / 25), abs=5.1e-5)
    # hbm-bound stage: fraction mirrors the bandwidth fraction
    vr = out["vol_regime"]
    assert vr["frac_of_peak"] == vr["frac_of_hbm"]
    # serial-scan stage: no peak to hold to
    assert out["newey_west"]["frac_of_peak"] is None


def test_roofline_unknown_chip_reports_null_fractions(bench, monkeypatch):
    class Dev:
        platform = "cpu"
        device_kind = "cpu"

    import jax

    monkeypatch.setattr(jax, "devices", lambda *a: [Dev()])
    models = bench._riskmodel_stage_models(700, 300, 31, 10, 42, 40, 4)
    out = bench._roofline({"eigen": 1.0}, {"eigen": models["eigen"]})
    assert out["eigen"]["frac_of_peak"] is None
    assert out["eigen"]["achieved_gflops"] > 0


def test_resolve_universe_named_and_numeric():
    """The --universe knob (PR 11): named universes pin the paper shapes,
    int-like specs scale N with csi300's other dims, and a bounded-T smoke
    run gets a _t<N> name suffix so its records can never masquerade as the
    full-history wall in the perfgate trajectory."""
    from mfm_tpu.data.synthetic import resolve_universe

    u = resolve_universe("csi300")
    assert (u.name, u.T, u.N, u.P, u.Q) == ("csi300", 1390, 300, 31, 10)
    a = resolve_universe("alla")
    assert (a.name, a.T, a.N) == ("alla", 2500, 5000)

    n = resolve_universe("999")
    assert (n.name, n.T, n.N, n.P, n.Q) == ("n999", 1390, 999, 31, 10)

    s = resolve_universe("csi300", T=32)
    assert s.name == "csi300_t32" and s.T == 32 and s.N == 300

    with pytest.raises(ValueError):
        resolve_universe("hk500")
    with pytest.raises(ValueError):
        resolve_universe("0")
