"""Integration: raw panel -> factor table -> barra assembly -> risk model."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from mfm_tpu.config import FactorConfig, PipelineConfig, RiskModelConfig, RollingSpec
from mfm_tpu.data.synthetic import synthetic_market_panel
from mfm_tpu.pipeline import (
    assemble_barra_table,
    run_factor_pipeline,
    run_risk_pipeline,
    shift_ret_next_period,
)


def test_shift_ret_is_next_traded_day():
    ret = np.array([
        [0.1, 0.01],
        [0.2, np.nan],
        [0.3, 0.03],
        [np.nan, 0.04],
    ])
    obs = np.isfinite(ret)
    out = shift_ret_next_period(ret, obs)
    # stock 0: next traded day's ret; last observed -> NaN
    np.testing.assert_allclose(out[:, 0], [0.2, 0.3, np.nan, np.nan], equal_nan=True)
    # stock 1 skips its suspension: day0 -> day2's ret
    np.testing.assert_allclose(out[:, 1], [0.03, np.nan, 0.04, np.nan], equal_nan=True)


@pytest.fixture(scope="module")
def full_run():
    data = synthetic_market_panel(T=140, N=30, n_industries=5, seed=11,
                                  missing=0.02, listing_gap=0.2)
    cfg = PipelineConfig(
        factors=FactorConfig(
            beta=RollingSpec(window=40, half_life=10, min_periods=8),
            rstr_total=60, rstr_lag=5, rstr_half_life=15, rstr_min_periods=8,
            dastd=RollingSpec(window=40, half_life=8, min_periods=8),
            cmra_window=30,
            stom=RollingSpec(window=10, min_periods=7),
            stoq=RollingSpec(window=21, min_periods=14),
            stoa=RollingSpec(window=42, min_periods=21),
        ),
        risk=RiskModelConfig(eigen_n_sims=8, eigen_sim_length=80),
        dtype="float64",
    )
    l1 = np.array([f"sw{c:02d}" for c in data["industry"]])
    fields = {k: data[k] for k in (
        "close", "total_mv", "circ_mv", "turnover_rate", "pb", "pe_ttm",
        "n_cashflow_act", "end_date_code", "q_profit_yoy", "q_sales_yoy",
        "total_ncl", "total_hldr_eqy_inc_min_int", "debt_to_assets",
    )}
    barra, factors = run_factor_pipeline(
        fields, data["index_close"], l1, data["dates"], data["stocks"], cfg
    )
    return data, cfg, barra, factors


def test_barra_table_schema(full_run):
    _, _, barra, _ = full_run
    assert list(barra.columns) == [
        "date", "stocknames", "capital", "ret", "industry",
        "size", "beta", "momentum", "residual_volatility", "non_linear_size",
        "book_to_price_ratio", "liquidity", "earnings_yield", "growth",
        "leverage",
    ]
    # one row per observed (stock, day)
    assert not barra.duplicated(["date", "stocknames"]).any()


def test_composites_respect_weights(full_run):
    _, _, _, f = full_run
    # leverage composite with all three present: exact weighted mean of the
    # *winsorized* components — recompute from raws via the posted pipeline
    lev = np.asarray(f["leverage"])
    comp = [np.asarray(f[c]) for c in ("MLEV", "DTOA", "BLEV")]
    # cells where all components are missing must be NaN
    all_missing = np.isnan(comp[0]) & np.isnan(comp[1]) & np.isnan(comp[2])
    assert np.all(np.isnan(lev[all_missing]))


def test_risk_pipeline_end_to_end(full_run):
    data, cfg, barra, _ = full_run
    res = run_risk_pipeline(barra_df=barra, config=cfg)
    T = res.arrays.ret.shape[0]
    K = len(res.arrays.factor_names())
    fr = res.factor_returns()
    assert fr.shape == (T, K)
    r2 = res.r_squared()["R2"].to_numpy()
    assert np.nanmean(r2) > 0.05  # synthetic returns have factor structure
    cov = res.final_covariance().to_numpy()
    np.testing.assert_allclose(cov, cov.T, rtol=1e-8)
    lam = res.lambda_series()["lambda"].to_numpy()
    assert np.isfinite(lam[-1]) and lam[-1] > 0


def test_ortho_makes_volatility_orthogonal(full_run):
    """After per-date orthogonalization, residual_volatility must be
    uncorrelated with BETA and SIZE on every date (the point of
    post_processing.py:47-69)."""
    _, _, _, f = full_run
    vol = np.asarray(f["volatility"])
    beta = np.asarray(f["BETA"])
    size = np.asarray(f["SIZE"])
    for t in range(90, 100):
        m = np.isfinite(vol[t]) & np.isfinite(beta[t]) & np.isfinite(size[t])
        if m.sum() < 5:
            continue
        # residuals of OLS on [1, beta, size] are orthogonal to regressors
        assert abs(np.corrcoef(vol[t][m], beta[t][m])[0, 1]) < 1e-6
        assert abs(np.corrcoef(vol[t][m], size[t][m])[0, 1]) < 1e-6
