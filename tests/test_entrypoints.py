"""Subprocess tests for the two driver entry points (`__graft_entry__.py`).

These are the only functions the harness actually calls, and round 1 shipped
with an un-tested hang in `dryrun_multichip` (bare device query initializing
the axon TPU plugin, which blocks when the tunnel is down — VERDICT.md weak
#1).  Each test runs the literal driver command in a fresh subprocess with a
hard timeout so a regression shows up as a test failure, not a driver
timeout.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, env_extra, timeout):
    env = dict(os.environ)
    # mimic the driver env: XLA_FLAGS carries the virtual device count; do
    # NOT pin JAX_PLATFORMS — surviving an env that points at a dead TPU
    # backend is exactly what these tests gate.
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


@pytest.mark.slow
def test_dryrun_multichip_8_devices_driver_command():
    # the literal driver gate: N virtual CPU devices, one sharded step
    proc = _run(
        "import __graft_entry__; __graft_entry__.dryrun_multichip(8)",
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip OK on 8 devices" in proc.stdout


def test_entry_exports_for_tpu_from_cpu_host():
    """Hardware-free TPU lowering gate for the WHOLE flagship step: AOT-
    export entry()'s program for platform 'tpu' from this CPU-only host.
    Catches Mosaic/XLA TPU lowering regressions anywhere in the pipeline
    (not just the eigh dispatch) without a TPU attached, and pins that the
    Pallas Jacobi kernel is actually part of the TPU program.

    Deliberately NOT slow-marked (measured ~6 s — lowering only, no
    compile/execute), same policy as the unmarked dryrun_multichip gate
    above: gates belong in the fast suite."""
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        # the suite env exports JAX_ENABLE_X64=true (conftest); production
        # runs x64 off, and Mosaic rejects x64-mode weak-f64 literals
        "jax.config.update('jax_enable_x64', False)\n"
        "from jax import export\n"
        "import __graft_entry__\n"
        "fn, args = __graft_entry__.entry()\n"
        "exp = export.export(jax.jit(fn), platforms=('tpu',))(*args)\n"
        "mod = str(exp.mlir_module())\n"
        "print('tpu export OK, mosaic:', 'tpu_custom_call' in mod)\n"
    )
    proc = _run(code, {}, timeout=420)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "tpu export OK, mosaic: True" in proc.stdout


@pytest.mark.slow
def test_entry_compiles_and_runs_single_chip():
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');\n"
        "import __graft_entry__\n"
        "fn, args = __graft_entry__.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('entry OK', [getattr(o, 'shape', None) for o in out])\n"
    )
    proc = _run(code, {}, timeout=420)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "entry OK" in proc.stdout
