"""Chained parity: NW -> eigen adjustment -> vol regime as one pipeline,
against the golden serial chain with injected draws — exercises the validity
masking between stages (the reference's try/except empty-DataFrame path)."""

import numpy as np
import jax.numpy as jnp

from mfm_tpu.models.eigen import eigen_risk_adjust_by_time
from mfm_tpu.models.newey_west import newey_west_expanding
from mfm_tpu.models.vol_regime import vol_regime_adjust_by_time

import golden


def test_full_covariance_stack_matches_golden_chain():
    rng = np.random.default_rng(17)
    T, K, M = 70, 5, 12
    e = 0.01 * rng.standard_normal((T, K))
    f = np.copy(e)
    for t in range(1, T):
        f[t] += 0.3 * f[t - 1]

    draws = rng.standard_normal((M, K, 150))
    d = draws - draws.mean(axis=-1, keepdims=True)
    sim_covs = np.einsum("mkt,mlt->mkl", d, d) / (150 - 1)

    # --- framework: batched/scan pipeline ---
    covs, valid = newey_west_expanding(jnp.asarray(f), q=2, half_life=252.0)
    ecov, evalid = eigen_risk_adjust_by_time(
        covs, valid, jnp.asarray(sim_covs), 1.4
    )
    vcov, lamb = vol_regime_adjust_by_time(jnp.asarray(f), ecov, evalid, 42.0)

    # --- golden: the reference's serial structure ---
    g_ecov = []
    for t in range(1, T + 1):
        try:
            nw = golden.golden_newey_west(f[:t], 2, 252.0)
            g_ecov.append(golden.golden_eigen_adj(nw, draws, 1.4))
        except ValueError:
            g_ecov.append(None)
    factor_var = np.array([
        np.full(K, np.nan) if c is None else np.diag(c) for c in g_ecov
    ])
    g_lamb = golden.golden_vol_regime(f, factor_var, tao=42.0)

    evalid = np.asarray(evalid)
    for t in range(T):
        if g_ecov[t] is None:
            assert not evalid[t]
            continue
        assert evalid[t]
        np.testing.assert_allclose(np.asarray(ecov[t]), g_ecov[t],
                                   rtol=1e-7, atol=1e-12)
    np.testing.assert_allclose(np.asarray(lamb), g_lamb, rtol=1e-8, atol=1e-12)
    # final adjusted covariance chains all three stages
    t = T - 1
    np.testing.assert_allclose(np.asarray(vcov[t]), g_ecov[t] * g_lamb[t] ** 2,
                               rtol=1e-7, atol=1e-12)
