"""Random-portfolio bias statistic (models/bias.py::portfolio_bias_stat):
loopy-NumPy golden parity, statistical calibration on model-generated
returns, and the RiskPipelineResult/CLI surface."""

import json

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp


def _golden_portfolio_bias(X, dval, covs, cov_valid, spec, ret, weights):
    """Per-(portfolio, date) loops, straight from the definition."""
    T, N, K = X.shape
    Q = weights.shape[0]
    z = np.full((Q, T - 1), np.nan)
    ok = np.zeros((Q, T - 1), bool)
    for qi in range(Q):
        for t in range(T - 1):
            sup = dval[t] & np.isfinite(spec[t])
            w = np.where(sup, weights[qi], 0.0)
            s = w.sum()
            if not cov_valid[t] or s <= 0:
                continue
            w = w / s
            x = X[t].T @ w
            fvar = x @ covs[t] @ x
            svar = np.sum(w**2 * np.where(sup, spec[t], 0.0) ** 2)
            sigma = np.sqrt(fvar + svar)
            if not (np.isfinite(sigma) and sigma > 0):
                continue
            r_next = np.where(sup & np.isfinite(ret[t + 1]), ret[t + 1], 0.0)
            z[qi, t] = float(w @ r_next) / sigma
            ok[qi, t] = True
    return z, ok


def test_portfolio_bias_matches_loopy_golden():
    from mfm_tpu.models.bias import bias_std, portfolio_bias_stat

    rng = np.random.default_rng(0)
    T, N, K, Q = 30, 12, 4, 5
    X = rng.standard_normal((T, N, K))
    dval = rng.random((T, N)) < 0.85
    A = rng.standard_normal((T, K, K))
    covs = np.einsum("tik,tjk->tij", A, A) / K + np.eye(K) * 0.1
    cov_valid = rng.random(T) < 0.8
    spec = np.abs(rng.standard_normal((T, N))) * 0.02
    spec[rng.random((T, N)) < 0.2] = np.nan
    ret = 0.02 * rng.standard_normal((T, N))
    ret[rng.random((T, N)) < 0.1] = np.nan
    weights = np.abs(rng.standard_normal((Q, N)))

    z, ok = portfolio_bias_stat(
        jnp.asarray(X), jnp.asarray(dval), jnp.asarray(covs),
        jnp.asarray(cov_valid), jnp.asarray(spec), jnp.asarray(ret),
        jnp.asarray(weights))
    gz, gok = _golden_portfolio_bias(X, dval, covs, cov_valid, spec, ret,
                                     weights)
    np.testing.assert_array_equal(np.asarray(ok), gok)
    np.testing.assert_allclose(np.asarray(z)[gok], gz[gok], rtol=1e-8)

    # bias_std == np.std over the valid entries
    b = np.asarray(bias_std(jnp.asarray(z), jnp.asarray(ok)))
    for qi in range(Q):
        want = np.std(gz[qi][gok[qi]]) if gok[qi].sum() >= 2 else np.nan
        np.testing.assert_allclose(b[qi], want, rtol=1e-8, equal_nan=True)


def test_portfolio_bias_calibrated_on_model_generated_returns():
    """Returns drawn exactly from the claimed model (country factor with
    known var + iid specific noise with known per-stock vol) must give
    bias ~ 1; doubling the claimed factor vol must push bias well below 1
    (and the mirrored under-forecast above 1) — direction AND magnitude."""
    from mfm_tpu.models.bias import bias_std, portfolio_bias_stat

    rng = np.random.default_rng(3)
    T, N, Q = 900, 20, 30
    sf, ss = 0.01, 0.02
    X = np.ones((T, N, 1))                       # country-only design, K=1
    dval = np.ones((T, N), bool)
    cov_valid = np.ones(T, bool)
    spec = np.full((T, N), ss)
    f = sf * rng.standard_normal(T)
    eps = ss * rng.standard_normal((T, N))
    ret = f[:, None] + eps                       # ret[t] is the t-label
    weights = np.abs(rng.standard_normal((Q, N)))

    def bias_for(claimed_sf):
        covs = np.full((T, 1, 1), claimed_sf**2)
        z, ok = portfolio_bias_stat(
            jnp.asarray(X), jnp.asarray(dval), jnp.asarray(covs),
            jnp.asarray(cov_valid), jnp.asarray(spec), jnp.asarray(ret),
            jnp.asarray(weights))
        return np.asarray(bias_std(jnp.asarray(z), jnp.asarray(ok)))

    b = bias_for(sf)
    assert np.isfinite(b).all()
    assert abs(b.mean() - 1.0) < 0.1, b.mean()
    over = bias_for(2 * sf)                      # overforecast -> bias < 1
    assert over.mean() < 0.85
    under = bias_for(sf / 2)                     # underforecast -> bias > 1
    assert under.mean() > 1.15


def test_pipeline_portfolio_bias_and_cli(tmp_path, capsys):
    from mfm_tpu.cli import main
    from mfm_tpu.config import PipelineConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline

    df, _ = synthetic_barra_table(T=120, N=30, P=3, Q=2, seed=4)
    res = run_risk_pipeline(
        barra_df=df,
        config=PipelineConfig(risk=RiskModelConfig(eigen_n_sims=4)))
    rep = res.portfolio_bias(n_portfolios=8, seed=1, burn_in=60,
                             min_periods=5)
    assert rep["n_portfolios"] == 8
    assert len(rep["all_valid_dates"]["bias"]) == 8
    assert rep["all_valid_dates"]["mean"] is not None
    assert "after_burn_in_60" in rep

    # the same surface through the CLI
    barra = str(tmp_path / "b.csv")
    df.to_csv(barra, index=False)
    out = str(tmp_path / "res")
    main(["risk", "--barra", barra, "--out", out, "--eigen-sims", "4",
          "--portfolio-bias", "6"])
    capsys.readouterr()
    rec = json.load(open(f"{out}/portfolio_bias.json"))
    assert rec["n_portfolios"] == 6
    assert len(rec["all_valid_dates"]["bias"]) == 6
