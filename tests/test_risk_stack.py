"""Parity: Newey-West scan, eigenfactor adjustment, vol-regime scan, bias
stats vs loopy NumPy goldens."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.models.newey_west import newey_west, newey_west_expanding
from mfm_tpu.models.eigen import (
    eigen_risk_adjust,
    eigen_risk_adjust_by_time,
    simulated_eigen_covs,
)
from mfm_tpu.models.vol_regime import vol_regime_adjust_by_time
from mfm_tpu.models.bias import eigenfactor_bias_stat, bayes_shrink

import golden


@pytest.fixture(scope="module")
def fret():
    rng = np.random.default_rng(7)
    T, K = 90, 5
    # AR-ish factor returns so NW lag terms matter
    e = 0.01 * rng.standard_normal((T, K))
    f = np.copy(e)
    for t in range(1, T):
        f[t] += 0.4 * f[t - 1]
    return f


def test_newey_west_single_matches_golden(fret):
    V = np.asarray(newey_west(jnp.asarray(fret), q=2, half_life=252.0))
    G = golden.golden_newey_west(fret, q=2, tao=252.0)
    np.testing.assert_allclose(V, G, rtol=1e-10, atol=1e-16)


def test_newey_west_expanding_matches_per_window(fret):
    T, K = fret.shape
    covs, valid = newey_west_expanding(jnp.asarray(fret), q=2, half_life=252.0)
    covs, valid = np.asarray(covs), np.asarray(valid)
    for t in range(1, T + 1):
        if t <= 2 or t <= K:
            assert not valid[t - 1]
            continue
        assert valid[t - 1]
        G = golden.golden_newey_west(fret[:t], q=2, tao=252.0)
        np.testing.assert_allclose(covs[t - 1], G, rtol=1e-8, atol=1e-14)


def test_newey_west_expanding_jits_and_scales(fret):
    f = jnp.asarray(np.tile(fret, (1, 8)))  # K=40, close to the real K=39
    covs, valid = jax.jit(lambda r: newey_west_expanding(r, 2, 252.0))(f)
    assert covs.shape == (fret.shape[0], 40, 40)


def test_eigen_adjust_matches_golden_with_injected_draws(fret):
    K = fret.shape[1]
    cov = golden.golden_newey_west(fret, 2, 252.0)
    rng = np.random.default_rng(3)
    draws = rng.standard_normal((16, K, 200))
    G = golden.golden_eigen_adj(cov, draws, scale_coef=1.4)
    d = draws - draws.mean(axis=-1, keepdims=True)
    sim_covs = np.einsum("mkt,mlt->mkl", d, d) / (200 - 1)
    A = np.asarray(eigen_risk_adjust(jnp.asarray(cov), jnp.asarray(sim_covs), 1.4))
    np.testing.assert_allclose(A, G, rtol=1e-8, atol=1e-14)


def test_eigen_adjust_by_time_masks_invalid(fret):
    covs, valid = newey_west_expanding(jnp.asarray(fret), q=2, half_life=252.0)
    sim = simulated_eigen_covs(jax.random.key(0), fret.shape[1], 100, 8,
                               dtype=jnp.float64)
    out, ok = eigen_risk_adjust_by_time(covs, valid, sim, 1.4)
    out, ok = np.asarray(out), np.asarray(ok)
    assert np.all(np.isnan(out[~ok]))
    assert np.all(np.isfinite(out[ok]))
    # adjustment preserves symmetry and total variance direction
    for t in np.nonzero(ok)[0][:5]:
        np.testing.assert_allclose(out[t], out[t].T, rtol=1e-10)


def test_eigen_adjust_exactly_singular_cov_stays_finite(fret):
    """A covariance with an exactly-zero eigenvalue (rank-deficient NW
    window) must not poison the date with 0/0 NaN: the zero direction
    contributes v^2 * 0 to the rebuild, so the date stays valid and finite,
    and the nonzero directions match the full-rank computation restricted to
    them."""
    K = fret.shape[1]
    rng = np.random.default_rng(9)
    draws = rng.standard_normal((8, K, 200))
    d = draws - draws.mean(axis=-1, keepdims=True)
    sim_covs = jnp.asarray(np.einsum("mkt,mlt->mkl", d, d) / (200 - 1))

    # diagonal with an exact 0.0 entry: eigh returns the zero eigenvalue
    # exactly, so the Dm == 0 guard path is hit deterministically
    evals = np.array([0.0] + list(1e-4 * (1 + np.arange(K - 1))))
    cov = np.diag(evals)
    out, ok = eigen_risk_adjust_by_time(
        jnp.asarray(cov)[None], jnp.ones((1,), bool), sim_covs, 1.4
    )
    out, ok = np.asarray(out[0]), bool(ok[0])
    assert ok
    assert np.isfinite(out).all()
    # the zero direction stays (numerically) zero in the adjusted covariance
    np.testing.assert_allclose(out[:, 0], 0.0, atol=1e-12)
    np.testing.assert_allclose(out[0, :], 0.0, atol=1e-12)

    # rank deficiency 2: both zero directions stay zero, and no nonzero
    # direction is deflated by a degenerate slot's bias (the pre-fix Pallas
    # slot order applied a zero-direction ratio to D0[2], scaling it by
    # (1-scale_coef)^2 = 0.16)
    evals2 = np.array([0.0, 0.0] + list(1e-4 * (1 + np.arange(K - 2))))
    cov2 = np.diag(evals2)
    out2, ok2 = eigen_risk_adjust_by_time(
        jnp.asarray(cov2)[None], jnp.ones((1,), bool), sim_covs, 1.4
    )
    out2, ok2 = np.asarray(out2[0]), bool(ok2[0])
    assert ok2 and np.isfinite(out2).all()
    np.testing.assert_allclose(out2[:2, :], 0.0, atol=1e-12)
    np.testing.assert_allclose(out2[:, :2], 0.0, atol=1e-12)
    assert (np.diag(out2)[2:] > 0.3 * evals2[2:]).all()


def test_sim_sweeps_gating_and_config_validation():
    """The sweep reduction only engages when the near-diagonality premise
    holds (sim_length >= 4*K), and bad eigen_sim_sweeps values raise at
    config construction instead of deep inside the kernel."""
    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.eigen import sim_sweeps_for
    from mfm_tpu.ops.eigh import _sweeps_for

    # deep near-diagonal regime (sim_length >= 32K): one more sweep off
    assert sim_sweeps_for(42, jnp.float32, 1390) == _sweeps_for(42, jnp.float32) - 3
    # moderate regime (4K <= sim_length < 32K)
    assert sim_sweeps_for(42, jnp.float32, 200) == _sweeps_for(42, jnp.float32) - 2
    # premise fails -> solver default, no reduction
    assert sim_sweeps_for(42, jnp.float32, 100) == _sweeps_for(42, jnp.float32)

    for good in ("auto", None, 1, 7):
        RiskModelConfig(eigen_sim_sweeps=good)
    for bad in ("Auto", "5", 0, -1, 2.5, True):
        with pytest.raises(ValueError, match="eigen_sim_sweeps"):
            RiskModelConfig(eigen_sim_sweeps=bad)


def test_vol_regime_matches_golden(fret):
    T, K = fret.shape
    rng = np.random.default_rng(5)
    var = 1e-4 * (1 + rng.random((T, K)))
    var[:10] = np.nan  # invalid early dates
    covs = np.stack([np.diag(v) for v in np.where(np.isnan(var), np.nan, var)])
    valid = ~np.isnan(var).any(axis=1)
    adj, lamb = vol_regime_adjust_by_time(
        jnp.asarray(fret), jnp.asarray(covs), jnp.asarray(valid), half_life=42.0
    )
    G = golden.golden_vol_regime(fret, var, tao=42.0)
    np.testing.assert_allclose(np.asarray(lamb), G, rtol=1e-9, atol=1e-12)
    t = T - 1
    np.testing.assert_allclose(
        np.asarray(adj[t]), covs[t] * G[t] ** 2, rtol=1e-9
    )


def test_bias_stat_runs_and_is_finite(fret):
    covs, valid = newey_west_expanding(jnp.asarray(fret), q=2, half_life=252.0)
    b = eigenfactor_bias_stat(covs, valid, jnp.asarray(fret), predlen=5)
    b = np.asarray(b)
    assert b.shape == (fret.shape[1],)
    assert np.all(np.isfinite(b))


def test_bayes_shrink_matches_loopy_numpy():
    rng = np.random.default_rng(11)
    N = 400
    vol = np.abs(rng.normal(0.02, 0.01, N))
    cap = np.exp(rng.normal(11, 1, N))
    got = np.asarray(bayes_shrink(jnp.asarray(vol), jnp.asarray(cap), 10, 1.0))
    # loopy golden (contract utils.py:133-168) with the same quantile edges
    qs = np.quantile(cap, np.linspace(0, 1, 11)[1:-1])
    group = np.searchsorted(qs, cap, side="left")
    expect = np.empty(N)
    for g in range(10):
        sel = group == g
        m = np.sum(vol[sel] * cap[sel]) / np.sum(cap[sel])
        s = np.sqrt(np.mean((vol[sel] - m) ** 2))
        a = 1.0 * np.abs(vol[sel] - m)
        v = a / (a + s)
        expect[sel] = v * m + (1 - v) * np.abs(vol[sel])
    np.testing.assert_allclose(got, expect, rtol=1e-10)


def test_newey_west_associative_matches_scan(fret):
    covs_s, valid_s = newey_west_expanding(jnp.asarray(fret), q=2, half_life=252.0)
    covs_a, valid_a = newey_west_expanding(jnp.asarray(fret), q=2,
                                           half_life=252.0, method="associative")
    np.testing.assert_array_equal(np.asarray(valid_s), np.asarray(valid_a))
    np.testing.assert_allclose(np.asarray(covs_a), np.asarray(covs_s),
                               rtol=1e-9, atol=1e-15)


def test_newey_west_associative_date_sharded(fret):
    """The sequence-parallel path with the date axis sharded over 8 devices."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mfm_tpu.parallel.mesh import make_mesh, use_mesh

    f = jnp.asarray(np.tile(fret, (1, 2)))  # K=10
    f = jnp.concatenate([f] * 2, axis=0)    # T=180... keep divisible by 8
    f = f[:176]
    mesh = make_mesh(8, 1)
    fs = jax.device_put(f, NamedSharding(mesh, P("date", None)))
    with use_mesh(mesh):
        covs, valid = jax.jit(
            lambda r: newey_west_expanding(r, 2, 252.0, method="associative")
        )(fs)
    base, _ = newey_west_expanding(f, 2, 252.0)
    np.testing.assert_allclose(np.asarray(covs), np.asarray(base),
                               rtol=1e-8, atol=1e-14)


def test_bias_stats_summary_scopes_and_nonfinite_handling():
    """The JSON-ready acceptance summary (models/bias.py): burn-in scope
    present iff post-burn-in valid dates exist; a non-finite rank becomes
    null but does NOT null the finite ranks' aggregates."""
    import json

    from mfm_tpu.models.bias import bias_stats_summary

    rng = np.random.default_rng(3)
    T, K = 400, 4
    f = jnp.asarray(0.01 * rng.standard_normal((T, K)))
    covs = jnp.broadcast_to(0.0001 * jnp.eye(K), (T, K, K))
    # one pathological date-0..9 window invalid; rest valid
    valid = jnp.asarray(np.arange(T) >= 10)

    s = bias_stats_summary(covs, valid, covs, valid, f, burn_in=252)
    assert set(s) == {"all_valid_dates", "after_burn_in_252"}
    for scope in s.values():
        for stats in scope.values():
            assert len(stats["bias"]) == K
            assert stats["mean_abs_dev_from_1"] is not None
    out = json.dumps(s)  # strict JSON round trip
    assert "NaN" not in out

    # short panel: no post-burn-in dates -> scope absent, file still valid
    s2 = bias_stats_summary(covs[:100], valid[:100], covs[:100], valid[:100],
                            f[:100], burn_in=252)
    assert set(s2) == {"all_valid_dates"}

    # a zero-variance rank (sigma=0 -> inf bias) nulls only itself
    covs_bad = jnp.broadcast_to(
        jnp.diag(jnp.asarray([0.0] + [1e-4] * (K - 1))), (T, K, K))
    s3 = bias_stats_summary(covs_bad, valid, covs_bad, valid, f, burn_in=252)
    st = s3["all_valid_dates"]["newey_west"]
    assert st["mean_abs_dev_from_1"] is not None
