"""Parity: statement dedup / as-of join / fill policy vs pandas merge_asof."""

import numpy as np
import pandas as pd

from mfm_tpu.data.pit import asof_join, dedup_statements, fill_missing


def _statements(rng, stocks, n_per=14):
    rows = []
    for s in stocks:
        ends = pd.date_range("2019-03-31", periods=n_per, freq="QE")
        for e in ends:
            # announcement 30-120 days after period end; occasional revisions
            for _ in range(1 + (rng.random() < 0.2)):
                ann = e + pd.Timedelta(days=int(rng.integers(30, 120)))
                rows.append((s, ann, e, rng.normal()))
    df = pd.DataFrame(rows, columns=["ts_code", "f_ann_date", "end_date", "val"])
    return df.sample(frac=1, random_state=0)  # shuffle


def test_dedup_keeps_latest_ann_then_latest_end():
    rng = np.random.default_rng(0)
    df = _statements(rng, ["A", "B"])
    out = dedup_statements(df)
    # one row per (stock, end_date): the one with max f_ann_date
    grp = df.sort_values("f_ann_date").groupby(["ts_code", "end_date"]).tail(1)
    assert not out.duplicated(["ts_code", "end_date"]).any()
    assert not out.duplicated(["ts_code", "f_ann_date"]).any()
    # every kept (stock, end) row carries the latest announcement for it
    m = out.merge(grp, on=["ts_code", "end_date"], suffixes=("", "_want"))
    assert (m["f_ann_date"] == m["f_ann_date_want"]).all()


def test_asof_join_matches_pandas_merge_asof():
    rng = np.random.default_rng(1)
    stocks = [f"S{i}" for i in range(17)]
    stmts = dedup_statements(_statements(rng, stocks))
    days = pd.bdate_range("2020-01-01", periods=260)
    daily = pd.DataFrame({
        "ts_code": np.repeat(stocks, len(days)),
        "trade_date": np.tile(days, len(stocks)),
        "close": rng.random(len(stocks) * len(days)),
    })
    # drop random rows to make universes ragged
    daily = daily.sample(frac=0.9, random_state=2)

    got = asof_join(daily, stmts[["ts_code", "f_ann_date", "val"]],
                    left_on="trade_date", right_on="f_ann_date")

    want_chunks = []
    for s in stocks:  # the reference's per-stock loop (load_data.py:53-60)
        lc = daily[daily.ts_code == s].sort_values("trade_date")
        rc = stmts[stmts.ts_code == s].sort_values("f_ann_date")
        want_chunks.append(pd.merge_asof(
            lc, rc[["ts_code", "f_ann_date", "val"]],
            left_on="trade_date", right_on="f_ann_date", by="ts_code",
            direction="backward",
        ))
    want = pd.concat(want_chunks, ignore_index=True)

    got = got.sort_values(["ts_code", "trade_date"]).reset_index(drop=True)
    want = want.sort_values(["ts_code", "trade_date"]).reset_index(drop=True)
    np.testing.assert_allclose(
        got["val"].to_numpy(float), want["val"].to_numpy(float), equal_nan=True
    )
    assert (got["f_ann_date"].isna() == want["f_ann_date"].isna()).all()


def test_fill_missing_ffill_then_zero():
    df = pd.DataFrame({
        "ts_code": ["A"] * 4 + ["B"] * 4,
        "trade_date": list(pd.bdate_range("2020-01-01", periods=4)) * 2,
        "x": [np.nan, 1.0, np.nan, 2.0, np.nan, np.nan, 3.0, np.nan],
    })
    out = fill_missing(df, ["x"])
    np.testing.assert_array_equal(
        out["x"].to_numpy(), [0.0, 1.0, 1.0, 2.0, 0.0, 0.0, 3.0, 3.0]
    )


def test_diagnose_statements_clean_and_dirty():
    from mfm_tpu.data.pit import diagnose_statements

    clean = pd.DataFrame({
        "ts_code": ["a", "a", "b"],
        "f_ann_date": pd.to_datetime(["2024-04-25", "2024-08-20",
                                      "2024-04-28"]),
        "end_date": pd.to_datetime(["2024-03-31", "2024-06-30",
                                    "2024-03-31"]),
    })
    rep = diagnose_statements(clean)
    assert rep["issue_counts"] == {} and rep["stocks"] == {}
    assert rep["n_rows"] == 3 and rep["n_stocks"] == 2

    dirty = pd.DataFrame({
        "ts_code": ["a", "a", "b", "c", "d", "d"],
        "f_ann_date": pd.to_datetime([
            "2024-04-25", "2024-04-25",   # a: duplicate announcement key
            None,                         # b: missing announcement
            "2024-03-01",                 # c: announced before period end
            "2024-04-25", "2024-08-20",   # d: clean
        ]),
        "end_date": pd.to_datetime([
            "2024-03-31", "2023-12-31",
            "2024-03-31",
            "2024-03-31",
            "2024-03-31", "2024-06-30",
        ]),
    })
    rep = diagnose_statements(dirty)
    assert rep["issue_counts"] == {"missing_ann": 1, "dup_ann": 2,
                                   "ann_before_end": 1}
    assert rep["stocks"] == {"a": ["dup_ann"], "b": ["missing_ann"],
                             "c": ["ann_before_end"]}


def test_diagnose_flags_duplicate_period_end():
    from mfm_tpu.data.pit import diagnose_statements

    df = pd.DataFrame({
        "ts_code": ["a", "a"],
        "f_ann_date": pd.to_datetime(["2024-04-25", "2024-04-26"]),
        "end_date": pd.to_datetime(["2024-03-31", "2024-03-31"]),
    })
    rep = diagnose_statements(df)
    # every row of the duplicate group is counted (dedup would keep one)
    assert rep["issue_counts"] == {"dup_end": 2}
    assert rep["stocks"] == {"a": ["dup_end"]}


def test_diagnose_rejects_non_statement_table():
    import pytest

    from mfm_tpu.data.pit import diagnose_statements

    prices = pd.DataFrame({"ts_code": ["a"], "trade_date": ["20240102"],
                           "close": [1.0]})
    with pytest.raises(ValueError, match="f_ann_date"):
        diagnose_statements(prices)
    with pytest.raises(ValueError, match="missing column"):
        diagnose_statements(pd.DataFrame())  # empty/typo'd collection


def test_etl_verify_diagnose_cli(tmp_path, capsys):
    import json

    from mfm_tpu.cli import main
    from mfm_tpu.data.etl import PanelStore

    store = PanelStore(str(tmp_path / "store"))
    store.insert("balancesheet", pd.DataFrame({
        "ts_code": ["a", "a", "b"],
        "f_ann_date": ["20240425", "20240425", "20240428"],
        "end_date": ["20240331", "20231231", "20240331"],
        "total_ncl": [1.0, 2.0, 3.0],
    }))
    main(["etl-verify", "--store", str(tmp_path / "store"),
          "--name", "balancesheet", "--diagnose"])
    rep = json.loads(capsys.readouterr().out)
    assert rep["collection"] == "balancesheet"
    assert rep["issue_counts"] == {"dup_ann": 2}
    assert rep["stocks"] == {"a": ["dup_ann"]}
