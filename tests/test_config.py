"""Config validation (PipelineConfig flags beyond the schema test)."""


def test_rolling_impl_validated():
    import pytest

    from mfm_tpu.config import PipelineConfig

    assert PipelineConfig().rolling_impl == "scan"
    assert PipelineConfig(rolling_impl="block").rolling_impl == "block"
    with pytest.raises(ValueError):
        PipelineConfig(rolling_impl="Scan")


def test_nw_method_validated():
    import pytest

    from mfm_tpu.config import RiskModelConfig

    with pytest.raises(ValueError, match="nw_method"):
        RiskModelConfig(nw_method="typo")
    assert RiskModelConfig(nw_method="associative").nw_method == "associative"
