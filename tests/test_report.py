"""Model-health report (mfm_tpu/utils/report.py): summary math against
hand-computed values on small result tables, plot rendering, and the CLI
driver — the framework's version of the reference's notebook QC eyeballing
(SURVEY §4: factor paths, R², λ, bias pictures)."""

import json
import os

import numpy as np
import pandas as pd
import pytest


def _write_results(tmp_path, with_bias=False, with_specific=False):
    rng = np.random.default_rng(0)
    dates = pd.bdate_range("2024-01-02", periods=120)
    factors = ["country", "size", "beta", "momentum", "growth",
               "leverage", "liquidity", "ind_a", "ind_b"]
    fr = pd.DataFrame(0.01 * rng.standard_normal((120, len(factors))),
                      index=dates, columns=factors)
    fr.iloc[0] = np.nan  # a leading all-NaN date (pre-burn-in), must drop
    fr.to_csv(tmp_path / "factor_returns.csv")
    r2 = pd.DataFrame({"R2": np.clip(rng.normal(0.3, 0.1, 120), 0, 1)},
                      index=dates)
    r2.to_csv(tmp_path / "r_squared.csv")
    lam = pd.DataFrame({"lambda": 1 + 0.1 * rng.standard_normal(120)},
                       index=dates)
    lam.to_csv(tmp_path / "lambda.csv")
    if with_specific:
        sp = pd.DataFrame(0.02 * rng.standard_normal((120, 5)), index=dates,
                          columns=[f"s{i}" for i in range(5)])
        sp.to_csv(tmp_path / "specific_returns.csv")
    if with_bias:
        # both scopes, as mfm_tpu.models.bias.bias_stats_summary writes them
        # (keys "all_valid_dates" and "after_burn_in_{n}"): the report must
        # prefer the burn-in-excluded one
        bias = {
            "all_valid_dates": {
                "newey_west": {"bias": [34.5, 1.2, 1.1, None],
                               "mean_abs_dev_from_1": 11.266},
                "eigen_adjusted": {"bias": [20.1, 1.0, 0.98, None],
                                   "mean_abs_dev_from_1": 6.373},
            },
            "after_burn_in_252": {
                "newey_west": {"bias": [1.4, 1.2, 1.1, None],
                               "mean_abs_dev_from_1": 0.2333},
                "eigen_adjusted": {"bias": [1.05, 1.0, 0.98, None],
                                   "mean_abs_dev_from_1": 0.0233},
            },
        }
        (tmp_path / "bias_stats.json").write_text(json.dumps(bias))
    return fr, r2, lam


def test_summary_matches_hand_computed(tmp_path):
    from mfm_tpu.utils.report import model_health_summary

    fr, r2, lam = _write_results(tmp_path)
    s = model_health_summary(str(tmp_path))

    valid = fr.dropna(how="all")
    assert s["dates"]["count"] == len(valid) == 119
    assert s["dates"]["first"] == str(valid.index[0].date())
    # per-factor cum return & annualized vol
    np.testing.assert_allclose(
        s["factors"]["size"]["cum_return"],
        valid["size"].fillna(0).cumsum().iloc[-1], rtol=1e-5)
    np.testing.assert_allclose(
        s["factors"]["beta"]["ann_vol"],
        valid["beta"].std(ddof=1) * np.sqrt(252), rtol=1e-5)
    np.testing.assert_allclose(s["r2"]["mean"], r2["R2"].mean(), atol=1e-5)
    np.testing.assert_allclose(s["lambda"]["last"], lam["lambda"].iloc[-1],
                               atol=1e-5)
    assert "bias" not in s and "specific_dispersion" not in s


def test_summary_optional_sections(tmp_path):
    from mfm_tpu.utils.report import model_health_summary

    _write_results(tmp_path, with_bias=True, with_specific=True)
    (tmp_path / "portfolio_bias.json").write_text(json.dumps({
        "n_portfolios": 7,
        "all_valid_dates": {"mean": 1.31, "median": 1.2,
                            "mean_abs_dev_from_1": 0.31},
        "after_burn_in_252": {"mean": 1.02, "median": 1.01,
                              "mean_abs_dev_from_1": 0.05},
    }))
    s = model_health_summary(str(tmp_path))
    # burn-in-excluded scope preferred over all_valid_dates
    assert s["bias"]["scope"] == "after_burn_in_252"
    assert s["bias"]["eigen_adjusted"]["mean_abs_dev_from_1"] == 0.0233
    assert s["portfolio_bias"] == {
        "scope": "after_burn_in_252", "n_portfolios": 7, "mean": 1.02,
        "median": 1.01, "mean_abs_dev_from_1": 0.05}
    sp = pd.read_csv(tmp_path / "specific_returns.csv", index_col=0)
    np.testing.assert_allclose(s["specific_dispersion"]["mean_xsec_std"],
                               sp.std(axis=1, ddof=1).mean(), atol=1e-5)

    # portfolio_risk.json and alpha_styles.json surface when present
    (tmp_path / "portfolio_risk.json").write_text(json.dumps({
        "date": "2020-06-30", "total_vol": 0.012,
        "factor_var": 1e-4, "specific_var": 4.4e-5,
        "factor_exposures": {"country": 1.0}}))
    (tmp_path / "alpha_styles.json").write_text(json.dumps({
        "alpha_01": {"expression": "-delta(close, 5)", "mean_ic": 0.03,
                     "score": 0.03}}))
    s = model_health_summary(str(tmp_path))
    assert s["portfolio_risk"] == {"date": "2020-06-30", "total_vol": 0.012,
                                   "factor_var": 1e-4,
                                   "specific_var": 4.4e-5}
    assert s["alpha_styles"]["alpha_01"]["expression"] == "-delta(close, 5)"


def test_missing_factor_returns_raises(tmp_path):
    from mfm_tpu.utils.report import model_health_summary

    with pytest.raises(FileNotFoundError):
        model_health_summary(str(tmp_path))


def test_plot_writes_png_both_variants(tmp_path):
    from mfm_tpu.utils.report import plot_model_health

    _write_results(tmp_path, with_bias=True)
    p1 = str(tmp_path / "health_bias.png")
    plot_model_health(str(tmp_path), p1)
    assert os.path.getsize(p1) > 5000
    os.remove(tmp_path / "bias_stats.json")  # vol-bars fallback panel
    p2 = str(tmp_path / "health_vols.png")
    plot_model_health(str(tmp_path), p2)
    assert os.path.getsize(p2) > 5000
    p3 = str(tmp_path / "health_k0.png")  # --top-k 0: everything folds gray
    plot_model_health(str(tmp_path), p3, top_k=0)
    assert os.path.getsize(p3) > 5000


def test_report_cli(tmp_path, capsys):
    from mfm_tpu.cli import main

    _write_results(tmp_path, with_bias=True)
    main(["report", "--results", str(tmp_path), "--plot", "health.png",
          "--json", "health.json"])
    out = json.loads(capsys.readouterr().out)
    assert out["dates"]["count"] == 119
    assert os.path.getsize(tmp_path / "health.png") > 5000
    on_disk = json.loads((tmp_path / "health.json").read_text())
    assert on_disk["r2"]["mean"] == out["r2"]["mean"]
