"""Flight recorder (mfm_tpu/obs/flightrec.py): the bounded event ring,
arming + triggered dumps, the torn-file validator, the breaker-open
integration (dump exactly once per open TRANSITION, stamped with the
triggering request's trace id), and the SIGKILL-mid-dump atomicity drill
(tier-1 runs the detection paths; the subprocess kill rides
``chaos``/``slow`` like the manifest and trace drills)."""

import json
import os
import signal
import subprocess
import sys

import pytest

from mfm_tpu.obs.flightrec import (
    FLIGHTREC_NAME,
    arm,
    armed_path,
    dump_flightrec,
    events,
    last_trace_id,
    read_flightrec,
    record_event,
    reset_flightrec,
    set_capacity,
    trigger_dump,
)
from mfm_tpu.obs.trace import end_span, reset_tracing, start_span

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_recorder():
    reset_flightrec()
    reset_tracing()
    yield
    reset_flightrec()
    reset_tracing()


# -- the event ring -----------------------------------------------------------

def test_ring_keeps_newest_oldest_first():
    set_capacity(3)
    for i in range(5):
        record_event("dispatch", replica=i)
    got = events()
    assert [ev["replica"] for ev in got] == [2, 3, 4]
    assert all(ev["kind"] == "dispatch" and "wall_ts" in ev for ev in got)


def test_set_capacity_validates_and_evicts_in_place():
    record_event("a")
    record_event("b")
    set_capacity(1)
    assert [ev["kind"] for ev in events()] == ["b"]
    with pytest.raises(ValueError, match="capacity"):
        set_capacity(0)


def test_last_trace_id_is_the_newest_stamped_event():
    assert last_trace_id() is None
    record_event("batch_error", trace_id="aa" * 16)
    record_event("breaker_open", reason="failures")   # no trace id
    assert last_trace_id() == "aa" * 16


# -- arming + dumps -----------------------------------------------------------

def test_trigger_dump_unarmed_is_a_noop(tmp_path):
    record_event("breaker_open")
    assert armed_path() is None
    assert trigger_dump("breaker_open") is None
    assert list(tmp_path.iterdir()) == []


def test_dump_roundtrips_and_overwrites(tmp_path):
    path = str(tmp_path / FLIGHTREC_NAME)
    arm(path)
    record_event("batch_error", trace_id="bb" * 16, detail="boom")
    end_span(start_span("serve.request", outcome="error"))
    assert trigger_dump("breaker_open",
                        state={"breaker": {"state": "open"}}) == path
    rec = read_flightrec(path)
    assert rec["trigger"] == "breaker_open"
    # the trace id defaults to the newest stamped event's — the
    # triggering request
    assert rec["trace_id"] == "bb" * 16
    assert [ev["kind"] for ev in rec["events"]] == ["batch_error"]
    assert [sp["name"] for sp in rec["spans"]] == ["serve.request"]
    assert rec["state"]["breaker"]["state"] == "open"
    assert isinstance(rec["metrics"], dict)
    # a later trigger overwrites: the newest postmortem wins
    record_event("wedge_quarantine", replica=1)
    trigger_dump("wedge_quarantine")
    rec2 = read_flightrec(path)
    assert rec2["trigger"] == "wedge_quarantine"
    assert len(rec2["events"]) == 2


@pytest.mark.parametrize("mangle, msg", [
    (lambda t: t[: len(t) // 2], "torn"),
    (lambda t: "[1, 2]", "JSON object"),
    (lambda t: json.dumps({"schema": 99}), "unsupported"),
    (lambda t: json.dumps({"schema": 1, "trigger": "x", "events": [],
                           "spans": [], "metrics": {}}), "missing 'state'"),
    (lambda t: json.dumps({"schema": 1, "trigger": "x", "events": {},
                           "spans": [], "metrics": {}, "state": {}}),
     "must be lists"),
])
def test_read_flightrec_rejects_torn_and_malformed(tmp_path, mangle, msg):
    path = str(tmp_path / FLIGHTREC_NAME)
    dump_flightrec(path, trigger="sigterm")
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(mangle(text))
    with pytest.raises(ValueError, match=msg):
        read_flightrec(path)


# -- breaker integration ------------------------------------------------------

def test_breaker_open_transition_dumps_exactly_once(tmp_path):
    """The dump fires on the closed->open TRANSITION, carrying the last
    failing request's trace id; further failures while already open must
    NOT rewrite the postmortem (the trigger context would be lost)."""
    from mfm_tpu.serve import CircuitBreaker

    path = str(tmp_path / FLIGHTREC_NAME)
    arm(path)
    br = CircuitBreaker(failures=2, cooldown_s=1e9)
    record_event("batch_error", trace_id="cc" * 16, detail="first")
    br.record_failure()
    assert not os.path.exists(path)        # still closed: no postmortem
    record_event("batch_error", trace_id="dd" * 16, detail="second")
    br.record_failure()
    assert br.state == "open"
    rec = read_flightrec(path)
    assert rec["trigger"] == "breaker_open"
    assert rec["trace_id"] == "dd" * 16
    assert rec["state"]["breaker"]["state"] == "open"
    stamp = os.stat(path).st_mtime_ns, rec["taken_at_unix"]
    record_event("batch_error", trace_id="ee" * 16, detail="while open")
    br.record_failure()                     # already open: no re-dump
    assert (os.stat(path).st_mtime_ns,
            read_flightrec(path)["taken_at_unix"]) == stamp


# -- crash atomicity ----------------------------------------------------------

_DUMP_SCRIPT = """\
import sys
sys.path.insert(0, {repo!r})
from mfm_tpu.obs import flightrec as fr
fr.arm({path!r})
fr.record_event("batch_error", trace_id="ab" * 16)
fr.trigger_dump("breaker_open", state={{"breaker": {{"state": "open"}}}})
"""


def _dump_in_subprocess(path, kill=False):
    env = dict(os.environ)
    env.pop("MFM_CHAOS_KILL", None)
    if kill:
        env["MFM_CHAOS_KILL"] = "flightrec.after_tmp"
    return subprocess.run(
        [sys.executable, "-c",
         _DUMP_SCRIPT.format(repo=REPO, path=path)],
        env=env, capture_output=True, text=True, timeout=120)


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_mid_dump_leaves_no_torn_file(tmp_path):
    path = str(tmp_path / FLIGHTREC_NAME)
    proc = _dump_in_subprocess(path, kill=True)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # the crash fell between tmp write and rename: no half-written
    # flightrec.json may exist for a postmortem reader to choke on
    assert not os.path.exists(path)
    assert _dump_in_subprocess(path).returncode == 0
    rec = read_flightrec(path)
    assert rec["trigger"] == "breaker_open"
    assert rec["trace_id"] == "ab" * 16
