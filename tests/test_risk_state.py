"""The resumable risk-model state (incremental daily-update path).

``RiskModel.init_state`` / ``RiskModel.update`` must continue the
full-history run BITWISE — ``assert_array_equal``, not a tolerance — across
warmup boundaries (t <= q, t <= K), single-date appends, multi-date slabs,
the npz checkpoint round trip, and appended dates whose Newey-West
covariance is non-PSD (the eigen-invalid path).  The final scan carries must
agree bitwise too, so a resumed history can keep resuming forever.

Donation discipline throughout: ``init_state``/``update`` donate their panel
inputs and (for update) the state carries, so every call gets fresh arrays
and states are copied before reuse.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mfm_tpu.config import RiskModelConfig
from mfm_tpu.data.artifacts import load_risk_state, save_risk_state
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.utils.contracts import assert_max_compiles

T, N, P, Q = 48, 24, 4, 3
K = 1 + P + Q
CFG = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48)


def _panels(seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(0, 0.02, (T, N)),
        rng.lognormal(10, 1, (T, N)),
        rng.normal(size=(T, N, Q)),
        rng.integers(0, P, (T, N)),
        rng.random((T, N)) > 0.05,
    )


def _model(panels, sl=slice(None), cfg=CFG):
    # fresh OWNED device arrays per call: init_state/update donate their
    # inputs, and jnp.asarray can zero-copy a same-dtype numpy view (the
    # bool valid panel) — donating that alias lets XLA scribble over the
    # fixture's memory.  jnp.array always copies.
    return RiskModel(*(jnp.array(np.asarray(p)[sl]) for p in panels),
                     n_industries=P, config=cfg)


def _copy(state):
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), state)


def _carries(state):
    return jax.tree_util.tree_leaves(
        (state.nw_carry, state.vr_num, state.vr_den))


def _assert_outputs_equal(got, want, msg):
    for i, name in enumerate(want._fields):
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(want[i]),
                                      err_msg=f"{msg}: {name}")


def _assert_carries_equal(a, b, msg):
    for x, y in zip(_carries(a), _carries(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)


@pytest.fixture(scope="module")
def panels():
    return _panels()


@pytest.fixture(scope="module")
def full(panels):
    """Full-history reference: outputs + final state from one init_state."""
    return _model(panels).init_state()


# T0 = 1, 2 sit inside the q-lag warmup (q = 2); 5 inside the t <= K
# invalid region (K = 8); 20/40 are plain mid-history cuts
@pytest.mark.parametrize("T0", [1, 2, 5, 20, 40])
def test_update_is_bitwise_suffix_of_full_run(panels, full, T0):
    full_out, full_state = full
    out0, st = _model(panels, slice(0, T0)).init_state()
    _assert_outputs_equal(
        out0, jax.tree_util.tree_map(lambda x: x[:T0], full_out),
        f"T0={T0} prefix")

    # one date at a time, the daily serving loop.  The 242x serving win is
    # a compile-once contract: after the first date compiles the
    # single-date signature, every later update must reuse it — shape or
    # dtype drift in the state pytree would retrace per day and trip the
    # guard on the remaining T - T0 - 1 iterations
    st_seq = _copy(st)
    o, st_seq = _model(panels, slice(T0, T0 + 1)).update(st_seq)
    rows = [o]
    with assert_max_compiles(1, what="daily update loop"):
        for t in range(T0 + 1, T):
            o, st_seq = _model(panels, slice(t, t + 1)).update(st_seq)
            rows.append(o)
    got = type(full_out)(*[
        np.concatenate([np.asarray(r[i]) for r in rows], axis=0)
        for i in range(len(full_out))])
    _assert_outputs_equal(
        got, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        f"T0={T0} sequential suffix")

    # the whole remainder as ONE slab
    o_slab, st_slab = _model(panels, slice(T0, T)).update(st)
    _assert_outputs_equal(
        o_slab, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        f"T0={T0} slab suffix")

    # N single-date appends, one slab, and the uninterrupted run all land
    # on the SAME carry — resumability is closed under composition
    _assert_carries_equal(st_seq, st_slab, f"T0={T0} seq-vs-slab carry")
    _assert_carries_equal(st_slab, full_state, f"T0={T0} slab-vs-full carry")


def test_fused_risk_step_compiles_once(panels, full):
    """The fused four-stage step and the daily-update step are pinned to
    one compilation each at a fixed signature: repeat calls at the same
    shapes/dtypes must hit the jit cache, not retrace."""
    warm = _model(panels).run_fused()  # warm the fused signature
    with assert_max_compiles(1, what="fused risk step"):
        again = _model(panels).run_fused()
    _assert_outputs_equal(again, warm, "fused repeat")

    _, st = _model(panels, slice(0, T - 1)).init_state()
    # warm the single-date update signature (the parametrized suffix tests
    # may or may not have run yet in this process — don't depend on order)
    _model(panels, slice(T - 1, T)).update(_copy(st))
    with assert_max_compiles(1, what="daily update step"):
        _model(panels, slice(T - 1, T)).update(_copy(st))


def test_state_npz_roundtrip_is_bitwise(panels, full, tmp_path):
    """A checkpoint written to disk and rehydrated in (what could be) a new
    process must continue exactly like the in-process state object."""
    full_out, _ = full
    T0 = 20
    _, st = _model(panels, slice(0, T0)).init_state()
    p = str(tmp_path / "state.npz")
    save_risk_state(p, _copy(st), meta={"note": "test"})
    loaded, meta = load_risk_state(p)
    assert meta["note"] == "test" and meta["kind"] == "risk_state"
    # identity must survive JSON (tuple-ness restored for the == check)
    assert loaded.stamp == st.stamp
    assert loaded.sim_length == st.sim_length
    assert loaded.eigen_batch_hint == st.eigen_batch_hint
    np.testing.assert_array_equal(np.asarray(loaded.sim_covs),
                                  np.asarray(st.sim_covs))
    _assert_carries_equal(loaded, st, "roundtrip carry")

    o_mem, _ = _model(panels, slice(T0, T)).update(st)
    o_disk, _ = _model(panels, slice(T0, T)).update(loaded)
    _assert_outputs_equal(o_disk, o_mem, "disk-vs-memory update")
    _assert_outputs_equal(
        o_disk, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        "disk update vs full run")


def test_appended_date_with_non_psd_nw_cov(tmp_path):
    """An appended date whose Newey-West covariance has a negative
    eigenvalue takes the eigen-invalid path (nw_valid & ~eigen_valid,
    vr_cov NaN) — and stays bitwise the full run, including the dates
    around it.  A short NW half-life concentrates the EWMA on ~3 effective
    samples against K=8 factors + 2 lag corrections, which is indefinite
    at several dates (verified below, not assumed)."""
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48,
                          nw_half_life=3.0)
    panels = _panels(seed=2)
    full_out, full_state = _model(panels, cfg=cfg).init_state()
    nwv = np.asarray(full_out.nw_valid)
    egv = np.asarray(full_out.eigen_valid)

    T0 = 30
    bad = np.nonzero(nwv[T0:] & ~egv[T0:])[0]
    assert bad.size, "panel no longer exercises the non-PSD path"
    assert egv[T0:].any(), "need valid dates around the invalid one"
    t_bad = T0 + bad[0]
    assert np.isnan(np.asarray(full_out.vr_cov)[t_bad]).all()

    _, st = _model(panels, slice(0, T0), cfg=cfg).init_state()
    o_slab, st_slab = _model(panels, slice(T0, T), cfg=cfg).update(st)
    _assert_outputs_equal(
        o_slab, jax.tree_util.tree_map(lambda x: x[T0:], full_out),
        "slab across a non-PSD date")
    _assert_carries_equal(st_slab, full_state, "carry across a non-PSD date")


def test_update_rejects_mismatched_identity(panels):
    """A checkpoint from one model identity must not silently continue
    under another: changed config, changed universe width — both raise."""
    T0 = 20
    _, st = _model(panels, slice(0, T0)).init_state()

    other_cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48,
                                nw_half_life=99.0)
    with pytest.raises(ValueError, match="stamp"):
        _model(panels, slice(T0, T), cfg=other_cfg).update(_copy(st))

    narrow = tuple(np.asarray(p)[:, :-1] for p in _panels())
    with pytest.raises(ValueError, match="stamp"):
        _model(narrow, slice(T0, T)).update(_copy(st))


def test_state_requires_scan_method(panels):
    """The resumable carry is the serial scan's; the associative method has
    no equivalent checkpoint, so both entry points refuse it."""
    cfg = RiskModelConfig(eigen_n_sims=8, eigen_sim_length=48,
                          nw_method="associative")
    with pytest.raises(ValueError, match="scan"):
        _model(panels, cfg=cfg).init_state()

    _, st = _model(panels, slice(0, 20)).init_state()
    st = dataclasses_replace_stamp(st, cfg)
    with pytest.raises(ValueError, match="scan"):
        _model(panels, slice(20, T), cfg=cfg).update(st)


def dataclasses_replace_stamp(st, cfg):
    """A state whose stamp claims ``cfg``'s identity (so update's method
    check, not the stamp check, is what fires)."""
    import dataclasses

    stamp = (st.stamp[0], st.stamp[1], st.stamp[2], st.stamp[3],
             cfg.identity())
    return dataclasses.replace(st, stamp=stamp)
