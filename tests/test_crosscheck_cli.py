"""Crosscheck tool, bias-stat plotting, and the QC CLI subcommands.

Reference parity: the jqdatasdk factor comparison (``beta.ipynb`` cells
29-30), the bias-statistic plot (``mfm/utils.py:116``), and the QC scripts
``verify_data.py`` / ``fill_missing_data.py`` (SURVEY.md §4)."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from mfm_tpu.utils.crosscheck import crosscheck_factors


@pytest.fixture
def factor_tables():
    rng = np.random.default_rng(0)
    dates = pd.to_datetime(["2024-01-02", "2024-01-03", "2024-01-04"])
    codes = [f"s{i:03d}.SZ" for i in range(40)]
    idx = pd.MultiIndex.from_product([dates, codes],
                                     names=["trade_date", "ts_code"])
    a = pd.DataFrame(index=idx).reset_index()
    a["size"] = rng.standard_normal(len(a))
    a["beta"] = rng.standard_normal(len(a))
    b = a.copy()
    # external agrees on size up to noise, uses a different scaling for beta
    b["size"] = a["size"] + 1e-6 * rng.standard_normal(len(a))
    b["beta"] = 2.0 * a["beta"] + 0.5
    # knock out some coverage on each side
    a.loc[:10, "size"] = np.nan
    b.loc[20:25, "size"] = np.nan
    return a, b


def test_crosscheck_statistics(factor_tables):
    a, b = factor_tables
    rep = crosscheck_factors(a, b)
    assert set(rep.index) == {"size", "beta"}
    # size: near-identical values
    assert rep.loc["size", "pearson"] > 0.999999
    assert rep.loc["size", "max_abs_diff"] < 1e-4
    # beta: affine rescaling -> perfect correlation, large abs diff
    assert rep.loc["beta", "pearson"] > 0.999999
    assert rep.loc["beta", "rank_corr"] > 0.999999
    assert rep.loc["beta", "max_abs_diff"] > 0.1
    # coverage reflects the knocked-out rows
    assert rep.loc["size", "coverage_ours"] < 1.0
    assert rep.loc["size", "coverage_ext"] < 1.0
    assert rep.loc["size", "n_overlap"] < len(a)


def test_crosscheck_duplicate_keys_not_double_counted(factor_tables):
    a, b = factor_tables
    # a raw vendor pull repeating every row must not inflate the overlap
    # (a cartesian merge would square the duplicated keys' weight)
    b_dup = pd.concat([b, b], ignore_index=True)
    rep = crosscheck_factors(a, b)
    rep_dup = crosscheck_factors(a, b_dup)
    pd.testing.assert_frame_equal(rep, rep_dup)


def test_crosscheck_disjoint_tables():
    a = pd.DataFrame({"trade_date": pd.to_datetime(["2024-01-02"]),
                      "ts_code": ["x"], "size": [1.0]})
    b = pd.DataFrame({"trade_date": pd.to_datetime(["2024-01-03"]),
                      "ts_code": ["y"], "size": [2.0]})
    rep = crosscheck_factors(a, b)
    assert rep.loc["size", "n_overlap"] == 0
    assert np.isnan(rep.loc["size", "pearson"])


def test_crosscheck_cli_roundtrip(factor_tables, tmp_path, capsys):
    from mfm_tpu.cli import main

    a, b = factor_tables
    pa, pb = str(tmp_path / "a.csv"), str(tmp_path / "b.csv")
    a.to_csv(pa, index=False)
    b.to_csv(pb, index=False)
    out = str(tmp_path / "report.csv")
    main(["crosscheck", "--ours", pa, "--external", pb, "--out", out])
    rep = json.loads(capsys.readouterr().out)
    assert rep["size"]["pearson"] > 0.999
    assert os.path.exists(out)


def test_crosscheck_explicit_factors_validated_and_sentinels_coerced():
    a = pd.DataFrame({"trade_date": pd.to_datetime(["2024-01-02"] * 3),
                      "ts_code": ["x", "y", "z"], "size": [1.0, 2.0, 3.0]})
    b = a.copy()
    b["size"] = ["1.0", "NULL", "3.0"]  # vendor sentinel -> object dtype
    rep = crosscheck_factors(a, b, factors=["size"])
    assert rep.loc["size", "n_overlap"] == 2
    with pytest.raises(ValueError, match="not found"):
        crosscheck_factors(a, b, factors=["Beta"])


def test_crosscheck_cli_int_yyyymmdd_dates_vs_parquet_datetimes(tmp_path, capsys):
    """The repo's native trade_date format is int yyyymmdd in CSVs; naive
    pd.to_datetime would read those as epoch nanoseconds and report zero
    overlap against a parquet side with real datetimes."""
    from mfm_tpu.cli import main

    a = pd.DataFrame({"trade_date": [20240102, 20240103],
                      "ts_code": ["x", "x"], "size": [1.0, 2.0]})
    b = pd.DataFrame({"trade_date": pd.to_datetime(["2024-01-02", "2024-01-03"]),
                      "ts_code": ["x", "x"], "size": [1.0, 2.0]})
    pa, pb = str(tmp_path / "a.csv"), str(tmp_path / "b.parquet")
    a.to_csv(pa, index=False)
    b.to_parquet(pb)
    main(["crosscheck", "--ours", pa, "--external", pb,
          "--factors", " size"])  # stray space must be stripped
    rep = json.loads(capsys.readouterr().out)
    assert rep["size"]["n_overlap"] == 2
    assert rep["size"]["max_abs_diff"] == 0.0


def test_plot_bias_stats_writes_png(tmp_path):
    from mfm_tpu.models.bias import plot_bias_stats

    path = str(tmp_path / "bias.png")
    plot_bias_stats({"before": np.linspace(0.8, 1.4, 10),
                     "after": np.ones(10)}, path)
    assert os.path.getsize(path) > 1000


def test_risk_cli_bias_plot(tmp_path, capsys):
    from mfm_tpu.cli import main
    from mfm_tpu.data.synthetic import synthetic_barra_table

    df, _ = synthetic_barra_table(T=50, N=25, P=3, Q=2, seed=1)
    barra = str(tmp_path / "barra.csv")
    df.to_csv(barra, index=False)
    out = str(tmp_path / "res")
    main(["risk", "--barra", barra, "--out", out, "--eigen-sims", "4",
          "--bias-plot", "bias.png"])
    assert os.path.getsize(os.path.join(out, "bias.png")) > 1000
    json.loads(capsys.readouterr().out)


def test_etl_cli_verify_and_missing(tmp_path, capsys):
    from mfm_tpu.cli import main
    from mfm_tpu.data.etl import PanelStore

    store = PanelStore(str(tmp_path / "store"))
    store.insert("stock_info", pd.DataFrame({"ts_code": ["a", "b", "c"]}))
    store.insert("daily_prices", pd.DataFrame({
        "ts_code": ["a", "a", "b"],
        "trade_date": ["20240102", "20240103", "20240102"],
        "close": [1.0, 1.1, 2.0],
    }))

    main(["etl-verify", "--store", str(tmp_path / "store")])
    rep = json.loads(capsys.readouterr().out)
    assert rep == {"rows": 3, "stocks": 2, "first_date": "20240102",
                   "last_date": "20240103"}

    main(["etl-missing", "--store", str(tmp_path / "store")])
    rep = json.loads(capsys.readouterr().out)
    assert rep == {"n_missing": 1, "missing": ["c"]}


def test_crosscheck_gate(tmp_path, capsys):
    from mfm_tpu.cli import main

    a = pd.DataFrame({"trade_date": [20240102, 20240103],
                      "ts_code": ["x", "x"],
                      "size": [1.0, 2.0], "beta": [0.5, 0.6]})
    b = a.copy()
    b["beta"] = [0.5, 0.7]  # 0.1 off
    pa, pb = str(tmp_path / "a.csv"), str(tmp_path / "b.csv")
    a.to_csv(pa, index=False)
    b.to_csv(pb, index=False)

    # within tolerance: clean exit
    main(["crosscheck", "--ours", pa, "--external", pb, "--gate", "0.2"])
    capsys.readouterr()
    # beyond tolerance: exit 1 naming the factor
    with pytest.raises(SystemExit) as ei:
        main(["crosscheck", "--ours", pa, "--external", pb, "--gate", "0.05"])
    assert ei.value.code == 1
    err = capsys.readouterr().err
    assert "GATE FAIL beta" in err and "GATE FAIL size" not in err
    # a factor with zero overlap must fail, not silently pass (NaN diff)
    b2 = b.copy()
    b2["size"] = np.nan
    b2.to_csv(pb, index=False)
    with pytest.raises(SystemExit):
        main(["crosscheck", "--ours", pa, "--external", pb, "--gate", "1.0"])
    assert "GATE FAIL size" in capsys.readouterr().err


def test_crosscheck_gate_empty_comparison_fails(tmp_path, capsys):
    from mfm_tpu.cli import main

    a = pd.DataFrame({"trade_date": [20240102], "ts_code": ["x"],
                      "size": [1.0]})
    b = a.rename(columns={"size": "size_f"})
    pa, pb = str(tmp_path / "a.csv"), str(tmp_path / "b.csv")
    a.to_csv(pa, index=False)
    b.to_csv(pb, index=False)
    # without --gate: reports an empty comparison and exits 0
    main(["crosscheck", "--ours", pa, "--external", pb])
    capsys.readouterr()
    # with --gate: a comparison of nothing must FAIL, not silently pass
    with pytest.raises(SystemExit) as ei:
        main(["crosscheck", "--ours", pa, "--external", pb, "--gate", "1.0"])
    assert ei.value.code == 1
    assert "no shared numeric factor columns" in capsys.readouterr().err
