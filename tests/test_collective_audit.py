"""The communication-layout doctrine (mfm_tpu/parallel/mesh.py) as a test:
XLA must implement the sharded stages with stock-axis reductions only —
no full-panel movement, and none at all for the rolling layout."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))

from collective_audit import build_report  # noqa: E402


def test_collective_doctrine_holds_on_virtual_mesh():
    rep = build_report(T=64, N=48, P=5, Q=3, meshes=((4, 2),))
    entry = rep["meshes"]["4x2"]
    # stock axis split in two -> the normal-equation / cap-sum contractions
    # must communicate, and only via reductions (plus the bounded K^2-sized
    # all-gather feeding the batched eigh, which XLA cannot partition)
    assert entry["regression"]["by_kind"].get("all-reduce", 0) >= 1
    assert entry["regression_is_reduce_only"]
    assert entry["rolling_is_communication_free"]
    assert entry["no_full_panel_collective"]
    assert rep["invariants_hold"]
