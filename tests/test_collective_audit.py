"""The communication-layout doctrine (mfm_tpu/parallel/mesh.py) as a test:
XLA must implement the sharded stages with stock-axis reductions only —
no full-panel movement, and none at all for the rolling layout."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))

from collective_audit import audit_hlo, build_report, check_invariants  # noqa: E402


def test_collective_doctrine_holds_on_virtual_mesh():
    rep = build_report(T=64, N=48, P=5, Q=3, meshes=((4, 2),))
    entry = rep["meshes"]["4x2"]
    # stock axis split in two -> the normal-equation / cap-sum contractions
    # must communicate, and only via reductions (plus the bounded K^2-sized
    # all-gather feeding the batched eigh, which XLA cannot partition)
    assert entry["regression"]["by_kind"].get("all-reduce", 0) >= 1
    assert entry["regression_is_reduce_only"]
    assert entry["rolling_is_communication_free"]
    assert entry["no_full_panel_collective"]
    assert rep["invariants_hold"]

    # the factored invariant check reproduces the report's verdict from the
    # raw stage audits — the importable path tests gate on
    inv = check_invariants(
        entry["regression"], entry["full_pipeline"], entry["rolling_beta"],
        panel_bytes=rep["panel_bytes"],
        eigh_gather_budget=entry["eigh_gather_budget_bytes"])
    assert inv["ok"]
    assert inv["rolling_is_communication_free"] \
        == entry["rolling_is_communication_free"]
    assert inv["no_full_panel_collective"] == entry["no_full_panel_collective"]
    assert inv["regression_is_reduce_only"] \
        == entry["regression_is_reduce_only"]


def test_check_invariants_rejects_panel_sized_collective():
    # a synthetic HLO with one panel-sized all-gather must fail the gate
    clean = audit_hlo("")
    bad = audit_hlo(
        "%all-gather.1 = f32[64,48]{1,0} all-gather(f32[64,24]{1,0} %p0)")
    panel_bytes = 64 * 48 * 4
    inv = check_invariants(bad, clean, clean, panel_bytes=panel_bytes,
                           eigh_gather_budget=1024)
    assert not inv["regression_is_reduce_only"]
    assert not inv["ok"]
    # and a reduce-only regression with bounded comms passes
    ok_reg = audit_hlo(
        "%all-reduce.1 = f32[14,14]{1,0} all-reduce(f32[14,14]{1,0} %p1)")
    inv2 = check_invariants(ok_reg, clean, clean, panel_bytes=panel_bytes,
                            eigh_gather_budget=1024)
    assert inv2["ok"]
